"""Measured record of the serving engine's two perf levers (serve.py).

The engine makes two throughput claims, each a measured-design decision:

- **Batched slots**: 8 concurrent requests through one slot bank vs the
  same requests served one at a time (slots=1) — decode is memory-bound
  per step, so batching rides along nearly free and the tunnel round-trip
  is shared by 8 streams.
- **Multi-token chunks**: k decode steps (including sampling) per
  dispatch via ``lax.scan`` vs one dispatch per token — on the tunneled
  chip every dispatch+fetch costs a ~100 ms round-trip (CLAUDE.md TIMING
  TRAP 2), so per-token cost at chunk k amortizes it k ways.

Timing discipline: every TextServer chunk ENDS in a D2H fetch of the
token block (the scheduler needs the values), so wall-clock around a
served workload is dispatch-inclusive and barrier-honest by construction
— exactly the quantity a serving client sees. The chunk sweep
additionally separates the per-dispatch fixed cost C from the marginal
per-token cost t by a least-squares fit of ``wall = (N/k)·C + N·t`` over
the chunk sizes — the two-point method generalized to the k-point chain.

Usage::

    python -m distributed_tensorflow_tpu.tools.serve_bench              # print
    python -m distributed_tensorflow_tpu.tools.serve_bench --write-docs # commit

``--write-docs`` writes docs/benchmarks/serving.md + serving.json;
tests/test_serve.py pins the committed md against the committed json
(the perf_record staleness pattern: a new artifact cannot land without
regenerating the doc).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp


def _build(model_kw=None):
    from distributed_tensorflow_tpu.models.gpt import GPTLM

    kw = dict(
        vocab_size=512,
        max_len=256,
        model_dim=128,
        num_heads=4,
        num_layers=2,
    )
    kw.update(model_kw or {})
    model = GPTLM(**kw)
    return model, model.init(seed=1)


def _workload(model, n_requests: int, max_new: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    sizes = rng.integers(8, 60, n_requests)
    prompts = [
        rng.integers(0, model.vocab_size, (int(s),)).astype(np.int32)
        for s in sizes
    ]
    from distributed_tensorflow_tpu.serve import GenerationConfig

    return prompts, GenerationConfig(max_new=max_new)


def _make_server(model, params, *, slots, chunk):
    """One server per (slots, chunk) config, WARMED once: jit caches live
    on the instance, so the measured runs below re-dispatch the compiled
    executables (a fresh server per run would re-trace — the first version
    of this bench did, and its 'per-token cost' was mostly tracing)."""
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer

    srv = TextServer(model, params, slots=slots, chunk=chunk, buckets=(64,))
    warm = [np.arange(1, 9, dtype=np.int32)] * min(2, slots)
    srv.generate(warm, GenerationConfig(max_new=max(2, chunk)))
    return srv


def _serve_wall(srv, prompts, cfg) -> float:
    """Wall seconds to serve the workload to completion on a warmed
    server. Each chunk's token fetch is the D2H barrier, so this is
    honest dispatch-inclusive time."""
    t0 = time.perf_counter()
    srv.generate(prompts, cfg)
    return time.perf_counter() - t0


def _serve_wall_tracked(srv, prompts, cfg):
    """Like :func:`_serve_wall` but drives the engine tick by tick,
    tracking peak concurrent occupancy (the slot-density observable)
    and the number of decode dispatches (the tokens/dispatch
    denominator for the speculation row). Both are read off the
    engine's dispatch SPANS, whose ``active`` attr snapshots occupancy
    while the dispatch ran — the ``slots_busy`` gauge is re-set to
    post-completion occupancy before ``step()`` returns, so reading it
    here would miss every tick that finished the last active slot
    (undercounting dispatches inflates tokens/dispatch)."""
    rids = [srv.submit(p, cfg) for p in prompts]
    n0 = len(srv.spans.spans)
    t0 = time.perf_counter()
    while srv.step():
        pass
    wall = time.perf_counter() - t0
    decode = [
        sp
        for sp in list(srv.spans.spans)[n0:]
        if sp["name"] in ("decode_chunk", "spec_verify")
    ]
    peak = max((sp["args"]["active"] for sp in decode), default=0)
    for r in rids:
        srv.result(r)
    return wall, peak, len(decode)


def bench_paged_density(
    *,
    slab_slots: int = 4,
    density_factor: int = 4,
    n_requests: int = 32,
    max_new: int = 40,
    block_size: int = 16,
    model_kw=None,
) -> dict:
    """Paged-vs-slab occupancy at EQUAL KV HBM on a short-request mix.

    The slab bank reserves ``slots × max_len`` positions regardless of
    request size; the paged pool holds the SAME number of positions
    (``kv_blocks × block_size = slab_slots × max_len``) but admits by
    actual footprint (``ceil((prompt+max_new)/bs)`` blocks), so short
    requests pack ``density_factor`` × more concurrent residents into
    identical memory. Measured, not asserted: peak concurrent occupancy
    is counted from dispatch-span ``active`` attrs while each server
    drains the same workload (``_serve_wall_tracked`` — NOT the
    ``slots_busy`` gauge, which is re-set to post-completion occupancy
    before ``step()`` returns and misses every tick that finishes the
    last active slot)."""
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer

    model, params = _build(model_kw)
    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(0, model.vocab_size, (int(s),)).astype(np.int32)
        for s in rng.integers(8, 25, n_requests)
    ]
    cfg = GenerationConfig(max_new=max_new)
    pool_positions = slab_slots * model.max_len
    paged_slots = slab_slots * density_factor
    kv_blocks = pool_positions // block_size

    slab = TextServer(
        model, params, slots=slab_slots, chunk=32, buckets=(32,)
    )
    paged = TextServer(
        model, params, slots=paged_slots, chunk=32, buckets=(32,),
        paged=True, block_size=block_size, kv_blocks=kv_blocks,
    )
    warm = [np.arange(1, 9, dtype=np.int32)] * 2
    slab.generate(warm, GenerationConfig(max_new=2))
    paged.generate(warm, GenerationConfig(max_new=2))

    slab_wall, slab_peak, _ = _serve_wall_tracked(slab, prompts, cfg)
    paged_wall, paged_peak, _ = _serve_wall_tracked(paged, prompts, cfg)
    total_tokens = n_requests * max_new
    return {
        "kv_hbm_positions": pool_positions,
        "block_size": block_size,
        "workload": {
            "requests": n_requests,
            "prompt_range": [8, 24],
            "max_new": max_new,
        },
        "slab": {
            "slots": slab_slots,
            "peak_occupancy": int(slab_peak),
            "wall_s": round(slab_wall, 4),
            "tokens_per_s": round(total_tokens / slab_wall, 1),
        },
        "paged": {
            "slots": paged_slots,
            "kv_blocks": kv_blocks,
            "peak_occupancy": int(paged_peak),
            "wall_s": round(paged_wall, 4),
            "tokens_per_s": round(total_tokens / paged_wall, 1),
        },
        "density_x": round(paged_peak / max(slab_peak, 1), 2),
        "throughput_x": round(slab_wall / paged_wall, 2),
    }


def bench_quantized_density(
    *,
    bf16_blocks: int = 64,
    block_size: int = 16,
    n_requests: int = 16,
    max_new: int = 144,
    slots: int = 12,
    kv_dtype: str = "int8",
    model_kw=None,
) -> dict:
    """Quantized-vs-bf16 paged pools at EQUAL KV HBM **bytes** (round
    15). The bf16 pool holds ``bf16_blocks``; the quantized pool gets
    the SAME byte budget through ``kv_hbm_bytes``, so its block count
    derives from the element size (int8 payload + the f32 per-row
    scales, charged honestly by ``serve_pool.kv_block_bytes``) — ~1.8×
    the blocks at these shapes. On a long-generation mix (every request
    reserves the same worst-case block count) the byte-smaller blocks
    also pack the bf16 pool's remainder, and the measured peak
    occupancy doubles: the ``slot_density_q`` series. Peaks are counted
    from dispatch-span ``active`` attrs exactly as
    :func:`bench_paged_density` does; occupancy is admission-control
    arithmetic (deterministic for a fixed workload), so the series is
    stable under the regression gate even off-chip — only the wall
    columns carry device provenance."""
    from distributed_tensorflow_tpu import serve_pool
    from distributed_tensorflow_tpu.ops.quantized import kv_elem_bytes
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer

    model, params = _build(model_kw)
    rng = np.random.default_rng(13)
    prompts = [
        rng.integers(0, model.vocab_size, (int(s),)).astype(np.int32)
        for s in rng.integers(17, 33, n_requests)
    ]
    cfg = GenerationConfig(max_new=max_new)
    budget = bf16_blocks * serve_pool.kv_block_bytes(
        block_size,
        num_layers=model.num_layers,
        kv_heads=model.num_kv_heads,
        head_dim=model.head_dim,
        elem_bytes=kv_elem_bytes("bf16", model.compute_dtype),
    )
    kw = dict(
        slots=slots, chunk=32, buckets=(32,), paged=True,
        block_size=block_size,
    )
    bf16 = TextServer(model, params, kv_blocks=bf16_blocks, **kw)
    quant = TextServer(
        model, params, kv_hbm_bytes=budget, kv_dtype=kv_dtype, **kw
    )
    warm = [np.arange(1, 9, dtype=np.int32)] * 2
    bf16.generate(warm, GenerationConfig(max_new=2))
    quant.generate(warm, GenerationConfig(max_new=2))

    bf16_wall, bf16_peak, _ = _serve_wall_tracked(bf16, prompts, cfg)
    q_wall, q_peak, _ = _serve_wall_tracked(quant, prompts, cfg)
    total_tokens = n_requests * max_new
    device = jax.devices()[0].device_kind
    return {
        "device": device,
        "kv_hbm_bytes": budget,
        "block_size": block_size,
        "workload": {
            "requests": n_requests,
            "prompt_range": [17, 32],
            "max_new": max_new,
        },
        "bf16": {
            "kv_blocks": bf16.kv_blocks,
            "positions": bf16.kv_blocks * block_size,
            "block_bytes": bf16.kv_block_bytes,
            "peak_occupancy": int(bf16_peak),
            "wall_s": round(bf16_wall, 4),
            "tokens_per_s": round(total_tokens / bf16_wall, 1),
        },
        "quantized": {
            "kv_dtype": kv_dtype,
            "kv_blocks": quant.kv_blocks,
            "positions": quant.kv_blocks * block_size,
            "block_bytes": quant.kv_block_bytes,
            "peak_occupancy": int(q_peak),
            "wall_s": round(q_wall, 4),
            "tokens_per_s": round(total_tokens / q_wall, 1),
        },
        "positions_x": round(quant.kv_blocks / bf16.kv_blocks, 2),
        "density_q_x": round(q_peak / max(bf16_peak, 1), 2),
    }


def bench_weight_only_decode(
    *,
    n_requests: int = 8,
    max_new: int = 64,
    slots: int = 4,
    chunk: int = 32,
    dtype: str = "int8",
    model_kw=None,
) -> dict:
    """Decode tokens/s A/B for the weight-only path: the same greedy
    workload through a full-precision server and one with
    ``decode_matmul_dtype`` set (projection weights pre-quantized at
    construction, ``wo_dot`` at every block matmul). The claim is HBM
    traffic — decode reads every weight per token — so CPU numbers are
    provenance only (the dequant-and-dot emulation can even run SLOWER
    there); the speedup column is a TUNNEL-TPU claim until the chip
    rerun, exactly like the round-13 ``matmul_dtype`` row."""
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer

    model, params = _build(model_kw)
    prompts, cfg = _workload(model, n_requests, max_new, seed=3)
    kw = dict(slots=slots, chunk=chunk, buckets=(64,))
    base = TextServer(model, params, **kw)
    wo = TextServer(model, params, decode_matmul_dtype=dtype, **kw)
    warm = [np.arange(1, 9, dtype=np.int32)] * 2
    base.generate(warm, GenerationConfig(max_new=4))
    wo.generate(warm, GenerationConfig(max_new=4))
    base_wall = min(_serve_wall(base, prompts, cfg) for _ in range(2))
    wo_wall = min(_serve_wall(wo, prompts, cfg) for _ in range(2))
    total_tokens = n_requests * max_new
    return {
        "device": jax.devices()[0].device_kind,
        "dtype": dtype,
        "workload": {"requests": n_requests, "max_new": max_new},
        "baseline_tokens_per_s": round(total_tokens / base_wall, 1),
        "wo_tokens_per_s": round(total_tokens / wo_wall, 1),
        "baseline_wall_s": round(base_wall, 4),
        "wo_wall_s": round(wo_wall, 4),
        "speedup": round(base_wall / wo_wall, 2),
    }


def bench_speculation(
    *,
    n_requests: int = 8,
    max_new: int = 96,
    spec_draft: int = 4,
    model_kw=None,
) -> dict:
    """Speculative decoding vs one-token-per-dispatch decode: the same
    greedy workload through (a) a paged server at chunk=1 (every token
    pays a dispatch) and (b) the same pool with n-gram drafts verified
    in one batched extend per tick. Reports the measured acceptance
    rate and tokens/dispatch — the quantity that beats 1.0 exactly when
    speculation amortizes the dispatch round-trip. Prompts carry
    repeated n-grams (the prompt-lookup drafter's food); greedy-exact
    acceptance means the streams are identical either way (the parity
    tests pin it), so this row is pure speed."""
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer

    model, params = _build(model_kw)
    rng = np.random.default_rng(11)
    prompts = []
    for _ in range(n_requests):
        pat = rng.integers(0, model.vocab_size, (8,)).astype(np.int32)
        prompts.append(np.tile(pat, 6)[: int(rng.integers(32, 49))])
    cfg = GenerationConfig(max_new=max_new)
    # slots=1 keeps batching out of the quotient: baseline
    # tokens/dispatch is exactly 1, so the spec row's excess over 1 is
    # pure speculation depth (speculation composes with batching — the
    # verify pass is one ragged extend across slots — but the record
    # should not conflate the two levers).
    kw = dict(slots=1, buckets=(64,), paged=True, block_size=16)

    base = TextServer(model, params, chunk=1, **kw)
    spec = TextServer(model, params, chunk=1, spec_draft=spec_draft, **kw)
    warm = [np.arange(1, 9, dtype=np.int32)] * 2
    base.generate(warm, GenerationConfig(max_new=4))
    spec.generate(warm, GenerationConfig(max_new=4))
    for c in ("spec_tokens_proposed", "spec_tokens_accepted"):
        spec.metrics.counter(c).value = 0.0  # drop warmup counts

    base_wall, _, base_disp = _serve_wall_tracked(base, prompts, cfg)
    spec_wall, _, spec_disp = _serve_wall_tracked(spec, prompts, cfg)
    proposed = int(spec.metrics.counter("spec_tokens_proposed").value)
    accepted = int(spec.metrics.counter("spec_tokens_accepted").value)
    total_tokens = n_requests * max_new
    return {
        "draft": spec_draft,
        "workload": {"requests": n_requests, "max_new": max_new},
        "proposed": proposed,
        "accepted": accepted,
        "acceptance_rate": round(accepted / max(proposed, 1), 3),
        "decode_dispatches": int(spec_disp),
        "baseline_dispatches": int(base_disp),
        "tokens_per_dispatch": round(total_tokens / max(spec_disp, 1), 2),
        "baseline_tokens_per_dispatch": round(
            total_tokens / max(base_disp, 1), 2
        ),
        "wall_s": round(spec_wall, 4),
        "baseline_wall_s": round(base_wall, 4),
        "speedup": round(base_wall / spec_wall, 2),
    }


def _decode_two_point(model, params, cache0, tok0, engine, *, k=16, reps=3):
    """Per-decode-step seconds via the TWO-POINT method (CLAUDE.md
    TIMING TRAP 2): time a warm k-step and a 4k-step compiled
    ``decode_slots`` chain and divide the DIFFERENCE by 3k, so the
    per-dispatch fixed cost (a ~100 ms round-trip on the tunneled chip)
    cancels instead of diluting into every step. Each measurement ends
    in a D2H token fetch BEFORE the clock read — the only trustworthy
    barrier."""
    import jax
    from jax import lax

    def chain(steps):
        @jax.jit
        def run(params, cache, tok):
            def body(carry, _):
                tok, cache = carry
                logits, cache = model.decode_slots(
                    params, tok, cache, engine=engine
                )
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return (nxt, cache), ()

            (tok, cache), _ = lax.scan(
                body, (tok, cache), None, length=steps
            )
            return tok

        return run

    run_k, run_4k = chain(k), chain(4 * k)
    int(run_k(params, cache0, tok0)[0])  # compile + warm
    int(run_4k(params, cache0, tok0)[0])

    def timed(fn):
        t0 = time.perf_counter()
        out = fn(params, cache0, tok0)
        _ = int(out[0])  # the fetch happens BEFORE perf_counter below
        return time.perf_counter() - t0

    vals = []
    for _ in range(reps):
        tk = timed(run_k)
        t4k = timed(run_4k)
        vals.append((t4k - tk) / (3 * k))
    return float(np.median(vals))


def bench_decode_engine(
    *,
    cache_lens: tuple[int, ...] = (256, 1024),
    kv_dtypes: tuple[str, ...] = ("bf16", "int8"),
    two_point_k: int = 16,
    model_kw=None,
) -> dict:
    """Fused-Pallas vs unrolled-XLA decode engine A/B (round 18): per
    (engine, kv_dtype, cache_len) config, µs/token over a slots=1
    ``decode_slots`` chain measured with the two-point method, the cache
    prefilled to half its length so attention spans a real resident
    cache. The PALLAS rows are measured ONLY on a real TPU backend —
    off-chip the kernel runs the Pallas *interpreter*, whose wall time
    is a correctness artifact, not a latency record (worse than
    meaningless: it would seed the gate band with garbage); skipped
    engines land in ``pending`` with that provenance, and the chip
    session's rerun (``--decode-engine``) fills them as a fresh
    device-keyed series."""
    import jax

    rows, pending = [], []
    device = jax.devices()[0].device_kind
    on_tpu = jax.default_backend() == "tpu"
    engines = ("xla", "pallas") if on_tpu else ("xla",)
    if not on_tpu:
        pending.append(
            {
                "engine": "pallas",
                "note": "interpreter-only off-TPU; rerun "
                "serve_bench --decode-engine on the chip",
            }
        )
    for c in cache_lens:
        mk = dict(
            vocab_size=512, max_len=c, model_dim=128, num_heads=4,
            num_layers=2,
        )
        mk.update(model_kw or {})
        model, params = _build(mk)
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, model.vocab_size, (c // 2,)).astype(
            np.int32
        )
        for kv in kv_dtypes:
            cache = model.empty_slot_cache(1, kv)
            _, cache = model.prefill_slots(
                params,
                cache,
                jnp.asarray(prompt[None, :]),
                jnp.asarray([prompt.size], jnp.int32),
                jnp.ones((1,), bool),
            )
            tok0 = jnp.zeros((1,), jnp.int32)
            for engine in engines:
                per_step = _decode_two_point(
                    model, params, cache, tok0, engine, k=two_point_k
                )
                rows.append(
                    {
                        "engine": engine,
                        "kv_dtype": kv,
                        "cache_len": int(c),
                        "us_per_token": round(per_step * 1e6, 2),
                        "tokens_per_s": round(1.0 / per_step, 1),
                    }
                )
    # Fused speedup per (kv, cache) pair when both engines measured.
    speedups = []
    for c in cache_lens:
        for kv in kv_dtypes:
            pair = {
                r["engine"]: r
                for r in rows
                if r["kv_dtype"] == kv and r["cache_len"] == c
            }
            if "xla" in pair and "pallas" in pair:
                speedups.append(
                    {
                        "kv_dtype": kv,
                        "cache_len": int(c),
                        "fused_speedup": round(
                            pair["xla"]["us_per_token"]
                            / pair["pallas"]["us_per_token"],
                            2,
                        ),
                    }
                )
    return {
        "device": device,
        "slots": 1,
        "two_point_steps": [two_point_k, 4 * two_point_k],
        "model": {"model_dim": 128, "num_layers": 2, "num_heads": 4},
        "rows": rows,
        "speedups": speedups,
        "pending": pending,
    }


def _count_dispatch_eqns(jaxpr) -> tuple[int, int]:
    """(kernel launches, cache-commit ops) in a traced jaxpr: Pallas
    launches are ``pallas_call`` eqns (counted whole — their interior
    kernel jaxpr is one launch, never recursed into); commit ops are
    the scatter family plus ``dynamic_update_slice``, the shapes XLA
    emits for the per-layer cache/scale writes the fused kernels fold
    into their aliased in-kernel DMA. Recurses through sub-jaxprs
    (pjit/scan/cond bodies) so engine-internal structure can't hide
    eqns from the count."""
    kernels = commits = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "pallas_call":
            kernels += 1
            continue
        if name.startswith("scatter") or name == "dynamic_update_slice":
            commits += 1
        for v in eqn.params.values():
            for x in v if isinstance(v, (tuple, list)) else (v,):
                sub = getattr(x, "jaxpr", x)
                if hasattr(sub, "eqns"):
                    k, s = _count_dispatch_eqns(sub)
                    kernels += k
                    commits += s
    return kernels, commits


def bench_decode_dispatches(
    *, cache_len: int = 256, kv_dtype: str = "int8", model_kw=None
) -> dict:
    """The CPU-deterministic half of the decode A/B (round 20):
    dispatches per decoded token, counted on the TRACED ``decode_slots``
    jaxpr rather than timed — launch counts are structural, identical on
    every device, so this half commits a gate-stable series off-chip
    while the µs/token rows stay pending for the v5e (the round-15
    slot-density precedent). Convention: dispatches/token = pallas_call
    eqns + cache-commit eqns (scatter family + dynamic_update_slice)
    + 1 for the sampling tail (same one XLA dispatch for every engine).
    The count is a structural proxy — XLA may fuse neighbouring commit
    ops — but the ordering it certifies is the tentpole claim: the
    unrolled XLA engine and the per-layer kernel both scale with
    num_layers (~S kernel/commit pairs), the megakernel is O(1) (ONE
    launch; the commit rides the kernel's input/output aliasing)."""
    import jax

    mk = dict(
        vocab_size=512, max_len=cache_len, model_dim=128, num_heads=4,
        num_layers=2,
    )
    mk.update(model_kw or {})
    model, params = _build(mk)
    cache = model.empty_slot_cache(1, kv_dtype)
    tok0 = jnp.zeros((1,), jnp.int32)
    act = jnp.ones((1,), bool)
    rows = []
    for engine in ("xla", "pallas-layer", "pallas"):

        def step(p, t, c, a, engine=engine):
            return model.decode_slots(p, t, c, a, engine=engine)

        jaxpr = jax.make_jaxpr(step)(params, tok0, cache, act)
        kernels, commits = _count_dispatch_eqns(jaxpr.jaxpr)
        rows.append(
            {
                "engine": engine,
                "kernel_launches": kernels,
                "commit_ops": commits,
                "dispatches_per_token": kernels + commits + 1,
            }
        )
    return {
        "device": "trace",
        "cache_len": int(cache_len),
        "kv_dtype": kv_dtype,
        "model": {
            "model_dim": mk["model_dim"],
            "num_layers": mk["num_layers"],
            "num_heads": mk["num_heads"],
        },
        "convention": "pallas_call + scatter-family/dynamic_update_slice "
        "eqns in one traced decode_slots step, +1 sampling tail",
        "rows": rows,
    }


def bench_fleet(
    *,
    replicas: int = 3,
    n_requests: int = 30,
    max_new: int = 32,
    slots: int = 2,
    chunk: int = 8,
    queue_limit: int = 64,
    kill_after_done: int = 3,
    model_kw=None,
    timeout_s: float = 900.0,
) -> dict:
    """Load generator over a REAL subprocess fleet (serve_fleet.py) with
    one mid-run SIGKILL: ≥3 replicas serve a greedy workload, the
    busiest replica is killed once a few requests completed (so the kill
    lands mid-decode with requests in flight), and the row records fleet
    throughput, TTFT/latency percentiles from the merged journals
    (``obs_report`` fleet reconstruction — the operator's own path), the
    failover count, and the FAILED-request count, which must be 0: the
    zero-loss contract, measured rather than asserted (the RUN_SLOW
    fault-injection test additionally pins token parity through the
    failover). Replicas run on CPU subprocesses regardless of the bench
    host — the row is a ROUTING/failover property (admission arithmetic
    + mailbox mechanics), not a model-speed claim; wall columns carry
    that provenance."""
    import shutil
    import signal
    import tempfile

    from distributed_tensorflow_tpu import serve_fleet
    from distributed_tensorflow_tpu.observability import aggregate
    from distributed_tensorflow_tpu.tools import obs_report

    mk = dict(
        vocab_size=512, max_len=256, model_dim=128, num_heads=4,
        num_layers=2,
    )
    mk.update(model_kw or {})
    model, params = _build(mk)
    fleet_dir = tempfile.mkdtemp(prefix="dtf-fleet-bench-")
    repo_root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )
    try:
        ckpt = os.path.join(fleet_dir, "ckpt")
        serve_fleet.publish_checkpoint(model, params, ckpt, step=1)
        env = {
            "PALLAS_AXON_POOL_IPS": "",  # replicas skip the axon plugin
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": os.environ.get("PYTHONPATH", "")
            + os.pathsep
            + repo_root,
        }
        router = serve_fleet.local_fleet(
            mk,
            ckpt,
            os.path.join(fleet_dir, "run"),
            replicas=replicas,
            slots=slots,
            chunk=chunk,
            queue_limit=queue_limit,
            buckets=(64,),
            env=env,
            min_replicas=1,
            max_restarts=2,
            backoff=0.5,
            probe_interval_s=0.25,
            poll_interval=0.02,
            print_fn=lambda *a: None,
        )
        rng = np.random.default_rng(17)
        prompts = [
            rng.integers(0, model.vocab_size, (int(s),)).astype(np.int32)
            for s in rng.integers(8, 49, n_requests)
        ]
        try:
            # Readiness gate: replica startup (jax import + restore +
            # first compile) is not serving — submitting before the
            # fleet is up would fold ~15 s of cold start into every TTFT.
            router.wait_until_up(timeout_s=timeout_s)
            for p in prompts:
                router.submit(p, {"max_new": max_new})
            t0 = time.perf_counter()
            killed = None
            deadline = t0 + timeout_s
            while router.step():
                st = router.stats()
                if killed is None and st["done"] >= kill_after_done:
                    victim = max(
                        router.replicas.values(),
                        key=lambda h: len(h.inflight),
                    )
                    if victim.inflight and victim.agent.handle is not None:
                        os.kill(victim.agent.handle.pid, signal.SIGKILL)
                        killed = victim.name
                if time.perf_counter() > deadline:
                    break  # failed requests show up in the count below
                time.sleep(0.02)
            wall = time.perf_counter() - t0
            stats = router.stats()
            failed = n_requests - stats["done"]
        finally:
            # Every exit path (FleetBelowFloor included) must stop the
            # replica subprocesses BEFORE the rmtree below deletes their
            # mailboxes out from under them.
            router.shutdown()
            router.journal.close()
        merged = aggregate.merge(os.path.join(fleet_dir, "run"))
        records = obs_report.reconstruct_fleet_requests(merged)
        pct = obs_report.request_percentiles(
            [
                {
                    "done": True,
                    "ttft_s": r["ttft_s"],
                    "latency_s": r["latency_s"],
                }
                for r in records
                # rid None = replica-local warmup traffic, not fleet load
                if r["done"] and r["rid"] is not None
            ]
        ) or {}
        total_tokens = stats["done"] * max_new
        return {
            "device": "cpu",  # subprocess replicas are pinned to CPU
            "replicas": replicas,
            "slots": slots,
            "chunk": chunk,
            "queue_limit": queue_limit,
            "workload": {
                "requests": n_requests,
                "max_new": max_new,
                "prompt_range": [8, 48],
            },
            "kill": {"victim": killed, "after_done": kill_after_done},
            "wall_s": round(wall, 4),
            "tokens_per_s": round(total_tokens / wall, 1),
            "failed_requests": int(failed),
            "failovers": stats["failovers"],
            "reroutes": stats["reroutes"],
            "ttft_s": pct.get("ttft_s"),
            "latency_s": pct.get("latency_s"),
        }
    finally:
        shutil.rmtree(fleet_dir, ignore_errors=True)


def _disagg_workload(vocab: int, n: int, seed: int):
    """The mixed workload disaggregation exists for: interleaved
    LONG-prefill/short-decode requests (summarization shape) and
    short-prefill/long-decode requests (chat shape). On a homogeneous
    fleet a long prefill admitted at a chunk boundary stalls every
    resident decoder on that replica for a full prefill dispatch;
    role-split replicas absorb prefills away from the decode stream."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        if i % 2:
            size = int(rng.integers(96, 161))   # long prefill ...
            max_new = 8                         # ... short continuation
        else:
            size = int(rng.integers(8, 25))     # chat: short prefill ...
            max_new = 40                        # ... long decode
        reqs.append(
            (rng.integers(0, vocab, (size,)).astype(np.int32), max_new)
        )
    return reqs


def _run_disagg_fleet(
    mk, reqs, *, roles, fleet_dir, env, slots, chunk, timeout_s,
    migrate_threshold=None, arrival_gap=0.0,
):
    """One side of the disagg A/B: serve ``reqs`` to completion on a
    fresh subprocess fleet (role-split or homogeneous — SAME paged cache
    geometry either way, so the only variable is routing topology) and
    return wall, per-request TTFT/latency percentiles from the merged
    journals, and the migration accounting. ``arrival_gap`` spaces the
    submissions (request i arrives at ``i * gap`` seconds): a streamed
    workload is the scenario disaggregation exists for — a one-burst
    submit admits everything in a single wave and levels the field, a
    stream keeps NEW prefills arriving while decodes are resident,
    which is exactly the interference role-splitting removes."""
    from distributed_tensorflow_tpu import serve_fleet
    from distributed_tensorflow_tpu.observability import aggregate
    from distributed_tensorflow_tpu.observability.journal import read_events
    from distributed_tensorflow_tpu.tools import obs_report

    router = serve_fleet.local_fleet(
        mk,
        os.path.join(os.path.dirname(fleet_dir), "ckpt"),
        fleet_dir,
        replicas=len(roles),
        roles=roles if any(r != "both" for r in roles) else None,
        slots=slots,
        chunk=chunk,
        queue_limit=64,
        buckets=(32, 192),
        paged=True,
        block_size=16,
        kv_blocks=96,
        env=env,
        min_replicas=1,
        max_restarts=2,
        backoff=0.5,
        probe_interval_s=0.25,
        poll_interval=0.02,
        print_fn=lambda *a: None,
        migrate_threshold=migrate_threshold,
    )
    try:
        router.wait_until_up(timeout_s=timeout_s)
        t0 = time.perf_counter()
        rids = []
        pending = list(enumerate(reqs))
        deadline = t0 + timeout_s
        while True:
            now = time.perf_counter() - t0
            while pending and pending[0][0] * arrival_gap <= now:
                _, (p, m) = pending.pop(0)
                rids.append(router.submit(p, {"max_new": m}))
            if not router.step() and not pending:
                break
            if time.perf_counter() > deadline:
                break
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        stats = router.stats()
        tokens = sum(
            len(router.result(rid)) for rid in rids if router.done(rid)
        )
    finally:
        router.shutdown()
        router.journal.close()
    merged = aggregate.merge(fleet_dir)
    records = obs_report.reconstruct_fleet_requests(merged)
    pct = obs_report.request_percentiles(
        [
            {"done": True, "ttft_s": r["ttft_s"], "latency_s": r["latency_s"]}
            for r in records
            if r["done"] and r["rid"] is not None
        ]
    ) or {}
    migr = [
        e for e in read_events(os.path.join(fleet_dir, "events.jsonl"))
        if e.get("kind") == "request_migrated"
    ]
    mig_bytes = [e["nbytes"] for e in migr if e.get("nbytes")]
    return {
        "roles": list(roles),
        "wall_s": round(wall, 4),
        "done": stats["done"],
        "failed_requests": len(reqs) - stats["done"],
        "tokens_per_s": round(tokens / wall, 1),
        "ttft_s": pct.get("ttft_s"),
        "latency_s": pct.get("latency_s"),
        "migrated": len(migr),
        "kv_migration_bytes_per_req": (
            round(sum(mig_bytes) / len(mig_bytes), 1) if mig_bytes else None
        ),
    }


def bench_disagg(
    *,
    n_requests: int = 32,
    slots=None,
    homog_slots: int = 16,
    chunk: int = 4,
    seed: int = 29,
    arrival_gap: float = 0.08,
    migrate_threshold: int | None = 32,
    model_kw=None,
    timeout_s: float = 900.0,
) -> dict:
    """The tentpole's A/B (round 23): the SAME mixed long-prefill/chat
    workload STREAMED (``arrival_gap`` seconds between arrivals) at a
    disaggregated fleet (2 prefill + 2 decode, two-leg migration) and a
    homogeneous fleet (4 both) — equal total replicas, equal paged-cache
    geometry, so the measured difference is the routing topology.
    Disaggregation must win BOTH TTFT p95 (chat decoders never stall
    behind a stranger's long prefill) and tokens/s (decode batches stay
    dense) to justify the migration payload it ships per request
    (``kv_migration_bytes_per_req`` — gate-covered, fails HIGH like
    every wire-bytes series). The config is role-TUNED, which is the
    point of roles: decode replicas pack more resident streams
    (``slots`` default [8, 8, 16, 16] per replica), short prompts skip
    migration entirely (``migrate_threshold``), while the homogeneous
    side gets the same max slot count uniformly. CPU subprocess
    replicas: a routing-topology property, not a model-speed claim; the
    TTFT for migrated requests is measured CONSERVATIVELY (the decode
    leg's first continuation token — the prefill leg's true first token
    lands earlier), so a disagg win here understates the real one."""
    import shutil
    import tempfile

    from distributed_tensorflow_tpu import serve_fleet

    mk = dict(
        vocab_size=512, max_len=256, model_dim=128, num_heads=4,
        num_layers=2,
    )
    mk.update(model_kw or {})
    model, params = _build(mk)
    reqs = _disagg_workload(model.vocab_size, n_requests, seed)
    root = tempfile.mkdtemp(prefix="dtf-disagg-bench-")
    repo_root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )
    env = {
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": os.environ.get("PYTHONPATH", "")
        + os.pathsep
        + repo_root,
    }
    try:
        serve_fleet.publish_checkpoint(
            model, params, os.path.join(root, "ckpt"), step=1
        )
        disagg = _run_disagg_fleet(
            mk, reqs,
            roles=["prefill", "prefill", "decode", "decode"],
            fleet_dir=os.path.join(root, "disagg"),
            env=env, slots=slots if slots is not None else [8, 8, 16, 16],
            chunk=chunk, timeout_s=timeout_s,
            migrate_threshold=migrate_threshold, arrival_gap=arrival_gap,
        )
        homog = _run_disagg_fleet(
            mk, reqs,
            roles=["both", "both", "both", "both"],
            fleet_dir=os.path.join(root, "homog"),
            env=env, slots=homog_slots, chunk=chunk, timeout_s=timeout_s,
            arrival_gap=arrival_gap,
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    d_p95 = (disagg.get("ttft_s") or {}).get("p95")
    h_p95 = (homog.get("ttft_s") or {}).get("p95")
    return {
        "device": "cpu",  # subprocess replicas are pinned to CPU
        "replicas": 4,
        "slots": slots if slots is not None else [8, 8, 16, 16],
        "homog_slots": homog_slots,
        "chunk": chunk,
        "seed": seed,
        "arrival_gap_s": arrival_gap,
        "migrate_threshold": migrate_threshold,
        "workload": {
            "requests": n_requests,
            "mix": "alternating long-prefill/short-decode (96-160 prompt, "
            "8 new) and chat (8-24 prompt, 40 new), streamed at one "
            "arrival per arrival_gap_s",
        },
        "disagg": disagg,
        "homogeneous": homog,
        "ttft_p95_speedup": (
            round(h_p95 / d_p95, 3) if d_p95 and h_p95 else None
        ),
        "tokens_per_s_speedup": round(
            disagg["tokens_per_s"] / homog["tokens_per_s"], 3
        ),
    }


def bench_load_gen(
    *,
    n: int = 48,
    rate: float = 150.0,
    slots: int = 2,
    chunk: int = 8,
    queue_limit: int = 16,
    seed: int = 21,
    model_kw=None,
) -> dict:
    """Overload row (round 21): the ``priority_mix`` load-gen scenario
    replayed at well over 2x capacity (``rate`` rps offered into
    ``slots`` slots behind a ``queue_limit``-deep queue), plus a
    ``steady`` baseline at the same shape. The measured contract —
    acceptance criteria of the round-21 scheduler, not aspirations:

    - every shed lands on the LOWEST class (batch p0), as a loud
      terminal ``RequestShed`` (the ``request_shed`` journal event the
      per-class summary is built from);
    - the deadline-capable classes (interactive p2, standard p1) lose
      NOTHING: ``hi_class_misses`` must be 0;
    - excess p0 arrivals that find no lower class to displace get
      round-16 ``QueueFull`` backpressure (the ``rejected`` column),
      never a silent drop.

    Per-class TTFT here is submit -> admission (the scheduler
    observable; see load_gen.summarize). The shed-rate magnitude is
    timing-dependent (how many arrivals catch a full queue), so the
    gate series carries it with the default tolerance; the ZERO on the
    hi classes is the hard claim and is also test-pinned."""
    from distributed_tensorflow_tpu.observability.journal import (
        EventJournal,
        read_events,
    )
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer
    from distributed_tensorflow_tpu.tools import load_gen

    import tempfile

    model, params = _build(model_kw)
    scenarios = {}
    for scenario, q in (("priority_mix", queue_limit), ("steady", None)):
        path = os.path.join(tempfile.mkdtemp(), "events.jsonl")
        journal = EventJournal(path, run_id="load_gen")
        srv = TextServer(
            model, params, slots=slots, chunk=chunk, buckets=(64,),
            queue_limit=q, journal=journal,
        )
        warm = [np.arange(1, 9, dtype=np.int32)] * min(2, slots)
        srv.generate(warm, GenerationConfig(max_new=4))
        reqs = load_gen.generate(
            scenario, seed=seed, n=n, vocab=model.vocab_size, rate=rate
        )
        out = load_gen.drive(srv, reqs, timeout_s=600.0)
        journal.close()
        workload = [e for e in read_events(path) if e.get("rid", -1) >= 2]
        summary = load_gen.summarize(workload)
        hi_miss = sum(
            c["requests"] - c["done"]
            for p, c in summary["classes"].items()
            if p > 0
        )
        lo_sheds = sum(
            c["shed"] for p, c in summary["classes"].items() if p == 0
        )
        all_sheds = sum(c["shed"] for c in summary["classes"].values())
        scenarios[scenario] = {
            "n": n,
            "rate_rps": rate,
            "queue_limit": q,
            "wall_s": round(out["wall_s"], 4),
            "rejected": out["rejected"],
            "hi_class_misses": int(hi_miss),
            "sheds_on_lowest_class_only": bool(lo_sheds == all_sheds),
            **summary,
        }
    return {
        "device": jax.devices()[0].device_kind,
        "slots": slots,
        "chunk": chunk,
        "seed": seed,
        "scenarios": scenarios,
    }


def bench_request_percentiles(
    model,
    params,
    *,
    n_requests: int = 24,
    max_new: int = 96,
    slots: int = 8,
    chunk: int = 32,
) -> dict | None:
    """Per-request TTFT/latency percentiles (round 12): the same batched
    workload served once more with an event journal attached, then the
    trace reconstruction (``obs_report.reconstruct_requests`` — the
    path an operator runs on a production journal) yields p50/p95/p99
    TTFT and end-to-end latency. A separate run, not a re-read of the
    headline rows: those stay journal-free so their methodology is
    unchanged. Warmup requests are dropped by rid."""
    import tempfile

    from distributed_tensorflow_tpu.observability.journal import (
        EventJournal,
        read_events,
    )
    from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer
    from distributed_tensorflow_tpu.tools import obs_report

    path = os.path.join(tempfile.mkdtemp(), "events.jsonl")
    journal = EventJournal(path)
    srv = TextServer(
        model, params, slots=slots, chunk=chunk, buckets=(64,),
        journal=journal,
    )
    warm = [np.arange(1, 9, dtype=np.int32)] * min(2, slots)
    srv.generate(warm, GenerationConfig(max_new=max(2, chunk)))
    prompts, cfg = _workload(model, n_requests, max_new)
    srv.generate(prompts, cfg)
    journal.close()
    records = [
        r
        for r in obs_report.reconstruct_requests(read_events(path))
        if r["rid"] >= len(warm)  # warmup rids precede the workload's
    ]
    pct = obs_report.request_percentiles(records)
    if pct is None:
        return None
    return {"slots": slots, "chunk": chunk, **pct}


def bench(
    *,
    n_requests: int = 24,
    max_new: int = 96,
    slots: int = 8,
    chunk: int = 32,
    chunk_sweep: tuple[int, ...] = (1, 8, 32, 64),
    model_kw=None,
) -> dict:
    model, params = _build(model_kw)
    prompts, cfg = _workload(model, n_requests, max_new)
    total_tokens = n_requests * max_new

    # -- batched vs sequential at the default chunk -----------------------
    srv_b = _make_server(model, params, slots=slots, chunk=chunk)
    srv_s = _make_server(model, params, slots=1, chunk=chunk)
    wall_batched = min(_serve_wall(srv_b, prompts, cfg) for _ in range(2))
    wall_seq = min(_serve_wall(srv_s, prompts, cfg) for _ in range(2))

    # -- per-token cost vs chunk size (one long request, slots=1) ---------
    long_prompt, long_cfg = _workload(model, 1, max_new=192, seed=1)
    sweep = []
    for k in chunk_sweep:
        srv_k = _make_server(model, params, slots=1, chunk=k)
        w = min(
            _serve_wall(srv_k, long_prompt, long_cfg) for _ in range(3)
        )
        sweep.append(
            {
                "chunk": int(k),
                "wall_s": round(w, 4),
                "per_token_ms": round(w * 1e3 / long_cfg.max_new, 3),
            }
        )
    # wall = b + (N/k)·C + N·t — least squares over the sweep for the
    # per-dispatch fixed cost C and marginal per-token cost t. The
    # intercept b absorbs the per-REQUEST constants (the prefill dispatch,
    # host scheduler setup): with N fixed across the sweep, omitting it
    # would fold those into t — the fixed-cost-diluted-into-the-marginal
    # artifact CLAUDE.md's TIMING TRAP 2 warns about.
    n_tok = long_cfg.max_new
    a = np.array([[1.0, n_tok / r["chunk"], n_tok] for r in sweep])
    y = np.array([r["wall_s"] for r in sweep])
    (req_b, fixed_c, marg_t), *_ = np.linalg.lstsq(a, y, rcond=None)

    k1 = next((r for r in sweep if r["chunk"] == 1), sweep[0])
    kbig = min(
        (r for r in sweep if r["chunk"] >= 32),
        key=lambda r: r["per_token_ms"],
        default=sweep[-1],
    )
    density = bench_paged_density(model_kw=model_kw)
    quantized = bench_quantized_density(model_kw=model_kw)
    weight_only = bench_weight_only_decode(model_kw=model_kw)
    speculation = bench_speculation(model_kw=model_kw)
    percentiles = bench_request_percentiles(
        model, params, n_requests=n_requests, max_new=max_new,
        slots=slots, chunk=chunk,
    )
    return {
        "device": jax.devices()[0].device_kind,
        "model": {
            "vocab": model.vocab_size,
            "model_dim": model.model_dim,
            "num_layers": model.num_layers,
            "max_len": model.max_len,
        },
        "workload": {
            "requests": n_requests,
            "max_new": max_new,
            "total_tokens": total_tokens,
        },
        "batched": {
            "slots": slots,
            "chunk": chunk,
            "wall_s": round(wall_batched, 4),
            "tokens_per_s": round(total_tokens / wall_batched, 1),
        },
        "sequential": {
            "slots": 1,
            "chunk": chunk,
            "wall_s": round(wall_seq, 4),
            "tokens_per_s": round(total_tokens / wall_seq, 1),
        },
        "batched_speedup": round(wall_seq / wall_batched, 2),
        "chunk_sweep": sweep,
        "chunk_speedup": round(
            k1["per_token_ms"] / kbig["per_token_ms"], 2
        ),
        "dispatch_fixed_ms": round(float(fixed_c) * 1e3, 3),
        "marginal_token_ms": round(float(marg_t) * 1e3, 3),
        "per_request_ms": round(float(req_b) * 1e3, 3),
        "paged_density": density,
        "quantized_density": quantized,
        "weight_only_decode": weight_only,
        "speculation": speculation,
        **(
            {"request_percentiles": percentiles}
            if percentiles is not None
            else {}
        ),
    }


# -- journal emission (round 10): the measured points as bench_point
# events, so BENCH artifacts, docs tables, and the event journal share
# one source (tools/perf_record.py --journal reads them back). ----------


def emit_bench_events(payload: dict, events_path: str) -> list[dict]:
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    j = EventJournal(events_path, run_id="serve_bench")
    try:
        common = dict(tool="serve_bench", device=payload["device"])
        return [
            j.emit(
                "bench_point", name="batched_tokens_per_s",
                value=payload["batched"]["tokens_per_s"], unit="tokens/s",
                slots=payload["batched"]["slots"],
                chunk=payload["batched"]["chunk"], **common,
            ),
            j.emit(
                "bench_point", name="sequential_tokens_per_s",
                value=payload["sequential"]["tokens_per_s"],
                unit="tokens/s", **common,
            ),
            j.emit(
                "bench_point", name="batched_speedup",
                value=payload["batched_speedup"], unit="x", **common,
            ),
            j.emit(
                "bench_point", name="chunk_speedup",
                value=payload["chunk_speedup"], unit="x", **common,
            ),
            j.emit(
                "bench_point", name="dispatch_fixed_ms",
                value=payload["dispatch_fixed_ms"], unit="ms", **common,
            ),
            j.emit(
                "bench_point", name="marginal_token_ms",
                value=payload["marginal_token_ms"], unit="ms", **common,
            ),
        ] + (
            [
                j.emit(
                    "bench_point", name="paged_slot_density",
                    value=payload["paged_density"]["density_x"], unit="x",
                    kv_hbm_positions=payload["paged_density"][
                        "kv_hbm_positions"
                    ],
                    **common,
                )
            ]
            if "paged_density" in payload
            else []
        ) + (
            [
                j.emit(
                    "bench_point", name="slot_density_q",
                    value=payload["quantized_density"]["density_q_x"],
                    unit="x",  # unit-aware gate: "x" fails LOW
                    kv_dtype=payload["quantized_density"]["quantized"][
                        "kv_dtype"
                    ],
                    kv_hbm_bytes=payload["quantized_density"][
                        "kv_hbm_bytes"
                    ],
                    **common,
                ),
                j.emit(
                    "bench_point", name="quantized_positions_x",
                    value=payload["quantized_density"]["positions_x"],
                    unit="x", **common,
                ),
            ]
            if "quantized_density" in payload
            else []
        ) + (
            [
                j.emit(
                    "bench_point", name="wo_decode_speedup",
                    value=payload["weight_only_decode"]["speedup"],
                    unit="x",
                    dtype=payload["weight_only_decode"]["dtype"],
                    **common,
                )
            ]
            # Gate this series ON-CHIP ONLY: the CPU number is a
            # dequant-and-dot emulation the bench itself documents as
            # meaningless off-chip (≈0.6-1.0× run to run) — a fail-low
            # band over it would flag container noise, not regressions.
            # The md row still carries the CPU A/B as provenance.
            if "weight_only_decode" in payload
            and payload["device"] != "cpu"
            else []
        ) + (
            [
                j.emit(
                    "bench_point", name="spec_tokens_per_dispatch",
                    value=payload["speculation"]["tokens_per_dispatch"],
                    unit="tokens/dispatch",
                    acceptance_rate=payload["speculation"][
                        "acceptance_rate"
                    ],
                    **common,
                )
            ]
            if "speculation" in payload
            else []
        ) + (
            [
                j.emit(
                    "bench_point", name="ttft_p95_s",
                    value=payload["request_percentiles"]["ttft_s"]["p95"],
                    unit="s",
                    requests=payload["request_percentiles"]["requests"],
                    **common,
                ),
                j.emit(
                    "bench_point", name="latency_p95_s",
                    value=payload["request_percentiles"]["latency_s"][
                        "p95"
                    ],
                    unit="s",
                    requests=payload["request_percentiles"]["requests"],
                    **common,
                ),
            ]
            if "request_percentiles" in payload
            else []
        )
    finally:
        j.close()


def emit_decode_events(payload: dict, events_path: str) -> list[dict]:
    """The decode-engine A/B's gate-covered series: one
    ``decode_us_per_token`` bench_point per measured (engine, kv_dtype,
    cache_len) config, unit ``us/token`` — lower-is-better after the
    round-18 unit-direction fix, so the gate fails HIGH on a latency
    regression. Config is encoded in the series NAME (the gate bands by
    (tool, name, device) — attrs alone would collapse every config into
    one band); pending (unmeasured) engines emit nothing, so the chip
    rerun starts those series fresh under its own device key."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    de = payload["decode_engine"]
    j = EventJournal(events_path, run_id="serve_bench")
    try:
        common = dict(tool="serve_bench", device=de["device"])
        return [
            j.emit(
                "bench_point",
                name=(
                    f"decode_us_per_token_{r['engine']}_{r['kv_dtype']}"
                    f"_c{r['cache_len']}"
                ),
                value=r["us_per_token"],
                unit="us/token",
                engine=r["engine"],
                kv_dtype=r["kv_dtype"],
                cache_len=r["cache_len"],
                **common,
            )
            for r in de["rows"]
        ]
    finally:
        j.close()


def emit_dispatch_events(payload: dict, events_path: str) -> list[dict]:
    """The dispatch-count half's gate series: one
    ``decode_dispatches_per_token_{engine}`` bench_point per engine,
    unit ``dispatches/token`` (LOWER_IS_BETTER — the gate fails HIGH if
    an engine ever regresses to more launches per token). Device key is
    the section's literal ``trace``: the count is structural, so its
    band must never collide with a cpu- or chip-keyed timing series.
    Emitted ONLY by ``--decode-dispatches`` — the µs/token series each
    carry exactly one committed point and a dispatch refresh must not
    append to them."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    disp = payload["decode_engine"]["dispatches"]
    j = EventJournal(events_path, run_id="serve_bench")
    try:
        common = dict(tool="serve_bench", device=disp["device"])
        return [
            j.emit(
                "bench_point",
                name=f"decode_dispatches_per_token_{r['engine']}",
                value=r["dispatches_per_token"],
                unit="dispatches/token",
                engine=r["engine"],
                kv_dtype=disp["kv_dtype"],
                cache_len=disp["cache_len"],
                **common,
            )
            for r in disp["rows"]
        ]
    finally:
        j.close()


def emit_fleet_events(payload: dict, events_path: str) -> list[dict]:
    """The fleet row's gate-covered bench_point series (round-12 gate:
    tokens/s fails LOW, the ttft ``s`` unit fails HIGH). The
    failed-request count rides along as a series too; its hard zero is
    pinned by the RUN_SLOW fault-injection test — the gate's band just
    keeps the trajectory on record."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    fl = payload["fleet"]
    j = EventJournal(events_path, run_id="serve_bench")
    try:
        common = dict(
            tool="serve_bench", device=fl.get("device", "cpu"),
            replicas=fl["replicas"],
        )
        out = [
            j.emit(
                "bench_point", name="fleet_tokens_per_s",
                value=fl["tokens_per_s"], unit="tokens/s", **common,
            ),
            j.emit(
                "bench_point", name="fleet_failed_requests",
                value=fl["failed_requests"], unit="requests", **common,
            ),
        ]
        if fl.get("ttft_s"):
            out.append(
                j.emit(
                    "bench_point", name="fleet_ttft_p95_s",
                    value=fl["ttft_s"]["p95"], unit="s", **common,
                )
            )
        return out
    finally:
        j.close()


def emit_disagg_events(payload: dict, events_path: str) -> list[dict]:
    """The disagg A/B's gate-covered series (round 23):
    ``disagg_ttft_p95_s`` (unit ``s``, fails HIGH — the chat tail
    regrowing under the same mixed load means prefill isolation broke),
    ``disagg_tokens_per_s`` (fails LOW), and
    ``kv_migration_bytes_per_req`` (unit ``bytes/req``, fails HIGH —
    the handoff payload creeping up is a wire regression, round-17
    bytes/token precedent)."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    dg = payload["disagg"]
    d = dg["disagg"]
    j = EventJournal(events_path, run_id="serve_bench")
    try:
        common = dict(
            tool="serve_bench", device=dg.get("device", "cpu"),
            replicas=dg["replicas"], seed=dg["seed"],
        )
        out = [
            j.emit(
                "bench_point", name="disagg_tokens_per_s",
                value=d["tokens_per_s"], unit="tokens/s", **common,
            ),
        ]
        if d.get("ttft_s"):
            out.append(
                j.emit(
                    "bench_point", name="disagg_ttft_p95_s",
                    value=d["ttft_s"]["p95"], unit="s", **common,
                )
            )
        if d.get("kv_migration_bytes_per_req") is not None:
            out.append(
                j.emit(
                    "bench_point", name="kv_migration_bytes_per_req",
                    value=d["kv_migration_bytes_per_req"],
                    unit="bytes/req", **common,
                )
            )
        return out
    finally:
        j.close()


def emit_load_gen_events(payload: dict, events_path: str) -> list[dict]:
    """The overload row's gate-covered per-class series (round 21):
    ``fleet_ttft_p95_p{k}_s`` (unit ``s``, fails HIGH — a scheduler
    regression shows up as interactive-tail inflation under the same
    load) and ``shed_rate_p{k}`` (unit ``shed_rate``, fails HIGH — more
    shedding at the same offered load is a capacity or scheduling
    regression; the regression_gate unit table lists it
    lower-is-better). Only the overload (priority_mix) scenario feeds
    the gate; the steady baseline is provenance in the md."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    lg = payload["load_gen"]
    sc = lg["scenarios"]["priority_mix"]
    j = EventJournal(events_path, run_id="serve_bench")
    try:
        common = dict(
            tool="serve_bench", device=lg["device"],
            scenario="priority_mix", seed=lg["seed"],
        )
        out = []
        for prio, c in sorted(sc["classes"].items()):
            p95 = (c.get("ttft_s") or {}).get("p95")
            if p95 is not None:
                out.append(
                    j.emit(
                        "bench_point", name=f"fleet_ttft_p95_p{prio}_s",
                        value=p95, unit="s", priority=int(prio), **common,
                    )
                )
            out.append(
                j.emit(
                    "bench_point", name=f"shed_rate_p{prio}",
                    value=c["shed_rate"], unit="shed_rate",
                    priority=int(prio), **common,
                )
            )
        return out
    finally:
        j.close()


# -- rendering (offline: the staleness guard re-renders committed JSON) ----


def render(payload: dict) -> str:
    b, s = payload["batched"], payload["sequential"]
    lines = [
        "| mode | slots | chunk | wall (s) | tokens/s |",
        "|---|---|---|---|---|",
        f"| batched | {b['slots']} | {b['chunk']} | {b['wall_s']} "
        f"| {b['tokens_per_s']} |",
        f"| sequential | {s['slots']} | {s['chunk']} | {s['wall_s']} "
        f"| {s['tokens_per_s']} |",
        "",
        f"**Batched speedup: {payload['batched_speedup']}x** "
        f"({payload['workload']['requests']} requests x "
        f"{payload['workload']['max_new']} tokens).",
        "",
        "| chunk k | per-token (ms) |",
        "|---|---|",
    ]
    for r in payload["chunk_sweep"]:
        lines.append(f"| {r['chunk']} | {r['per_token_ms']} |")
    lines += [
        "",
        f"**Chunking speedup: {payload['chunk_speedup']}x** per-token vs "
        "one-dispatch-per-token; fit wall = b + (N/k)·C + N·t gives "
        f"C = {payload['dispatch_fixed_ms']} ms/dispatch, "
        f"t = {payload['marginal_token_ms']} ms/token, "
        f"b = {payload.get('per_request_ms', 0.0)} ms/request "
        "(prefill + scheduler constants, kept out of t).",
    ]
    d = payload.get("paged_density")
    if d:
        sl, pg = d["slab"], d["paged"]
        lines += [
            "",
            "## Paged vs slab cache: slot density at equal KV HBM "
            f"({d['kv_hbm_positions']} cached positions, "
            f"block size {d['block_size']})",
            "",
            "| cache | slots | peak concurrent | wall (s) | tokens/s |",
            "|---|---|---|---|---|",
            f"| slab | {sl['slots']} | {sl['peak_occupancy']} "
            f"| {sl['wall_s']} | {sl['tokens_per_s']} |",
            f"| paged | {pg['slots']} ({pg['kv_blocks']} blocks) "
            f"| {pg['peak_occupancy']} | {pg['wall_s']} "
            f"| {pg['tokens_per_s']} |",
            "",
            f"**Slot density: {d['density_x']}x** concurrent residents "
            f"in identical KV memory (throughput {d['throughput_x']}x) "
            f"on a short-request mix (prompts "
            f"{d['workload']['prompt_range'][0]}-"
            f"{d['workload']['prompt_range'][1]} + "
            f"{d['workload']['max_new']} new of max_len "
            f"{payload['model']['max_len']}): the slab reserves "
            "worst-case slabs, the paged pool reserves actual "
            "footprints.",
        ]
    q = payload.get("quantized_density")
    if q:
        bq, qq = q["bf16"], q["quantized"]
        dev = f" ({q['device']})" if q.get("device") else ""
        lines += [
            "",
            "## Quantized KV cache: slot density at equal KV HBM bytes "
            f"({q['kv_hbm_bytes']} B budget, block size {q['block_size']})",
            "",
            "| pool | blocks | positions | bytes/block | peak concurrent "
            "| wall (s) | tokens/s |",
            "|---|---|---|---|---|---|---|",
            f"| bf16 | {bq['kv_blocks']} | {bq['positions']} "
            f"| {bq['block_bytes']} | {bq['peak_occupancy']} "
            f"| {bq['wall_s']}{dev} | {bq['tokens_per_s']} |",
            f"| {qq['kv_dtype']} | {qq['kv_blocks']} | {qq['positions']} "
            f"| {qq['block_bytes']} | {qq['peak_occupancy']} "
            f"| {qq['wall_s']}{dev} | {qq['tokens_per_s']} |",
            "",
            f"**Quantized slot density: {q['density_q_x']}x** peak "
            f"concurrent residents in the SAME byte budget "
            f"({q['positions_x']}x the cached positions — int8 payload "
            "plus the f32 per-row scales, charged honestly; the extra "
            "density over the positions ratio is the byte-smaller "
            "blocks packing the bf16 pool's remainder) on a "
            "long-generation mix (prompts "
            f"{q['workload']['prompt_range'][0]}-"
            f"{q['workload']['prompt_range'][1]} + "
            f"{q['workload']['max_new']} new). Occupancy is "
            "admission-control arithmetic — the density column carries "
            "over to the chip as-is; the wall columns are device-tagged "
            "provenance.",
        ]
    wo = payload.get("weight_only_decode")
    if wo:
        dev = f" ({wo['device']})" if wo.get("device") else ""
        lines += [
            "",
            "## Weight-only quantized decode (`decode_matmul_dtype`)",
            "",
            "| weights | tokens/s | wall (s) |",
            "|---|---|---|",
            f"| full precision | {wo['baseline_tokens_per_s']} "
            f"| {wo['baseline_wall_s']}{dev} |",
            f"| {wo['dtype']} (wo_dot) | {wo['wo_tokens_per_s']} "
            f"| {wo['wo_wall_s']}{dev} |",
            "",
            f"**Decode A/B: {wo['speedup']}x wall** on this device. The "
            "weight-only win is HBM traffic (decode reads every "
            "projection weight per token), so the CPU dequant-and-dot "
            "emulation understates — or inverts — the chip number; "
            "treat the speedup as TUNNEL-TPU until the v5e rerun, like "
            "the round-13 int8 training row.",
        ]
    de = payload.get("decode_engine")
    if de:
        dev = de.get("device", "?")
        lines += [
            "",
            "## Fused decode-step engine A/B (`decode_engine`, "
            "ops/pallas_decode.py)",
            "",
            "| engine | KV dtype | cache len | µs/token | tokens/s |",
            "|---|---|---|---|---|",
        ]
        for r in de["rows"]:
            lines.append(
                f"| {r['engine']} | {r['kv_dtype']} | {r['cache_len']} "
                f"| {r['us_per_token']} ({dev}) | {r['tokens_per_s']} |"
            )
        for s in de.get("speedups", []):
            lines += [
                "",
                f"**Fused speedup ({s['kv_dtype']}, C={s['cache_len']}): "
                f"{s['fused_speedup']}x** µs/token vs the unrolled XLA "
                "engine.",
            ]
        lines += [
            "",
            f"Two-point method (k = {de['two_point_steps'][0]} vs "
            f"{de['two_point_steps'][1]} warm compiled decode steps, "
            "slots=1, cache prefilled to half its length; Δ/(3k) with a "
            "D2H token fetch before every clock read), so the "
            "per-dispatch fixed cost cancels out of the per-token "
            "number.",
        ]
        for p in de.get("pending", []):
            lines.append(
                f"PENDING `{p['engine']}` rows: {p['note']} — the fused "
                "kernel's latency claim (one launch per block at L=1, "
                "int8/fp8 KV dequantized in-kernel) is measurable only "
                "where Mosaic compiles it."
            )
        disp = de.get("dispatches")
        if disp:
            m = disp["model"]
            lines += [
                "",
                "### Dispatches per token (traced — device-independent)",
                "",
                "| engine | kernel launches | commit ops "
                "| dispatches/token |",
                "|---|---|---|---|",
            ]
            for r in disp["rows"]:
                lines.append(
                    f"| {r['engine']} | {r['kernel_launches']} "
                    f"| {r['commit_ops']} "
                    f"| {r['dispatches_per_token']} |"
                )
            lines += [
                "",
                f"Counted on the traced `decode_slots` jaxpr "
                f"({m['num_layers']} layers, d={m['model_dim']}, "
                f"{disp['kv_dtype']} KV, C={disp['cache_len']}): "
                f"{disp['convention']}. The XLA engine and the "
                "per-layer kernel both scale with the layer count "
                "(a kernel/commit pair per layer); the megakernel is "
                "O(1) — one launch per token, the cache commit rides "
                "its input/output aliasing. Structural counts, not "
                "wall time: the gate series is committable off-chip "
                "(round-15 slot-density precedent).",
            ]
    sp = payload.get("speculation")
    if sp:
        lines += [
            "",
            "## Speculative decoding (n-gram drafts, greedy-exact "
            "verify)",
            "",
            "| mode | decode dispatches | tokens/dispatch | wall (s) |",
            "|---|---|---|---|",
            f"| chunk=1 baseline | {sp['baseline_dispatches']} "
            f"| {sp['baseline_tokens_per_dispatch']} "
            f"| {sp['baseline_wall_s']} |",
            f"| spec draft={sp['draft']} | {sp['decode_dispatches']} "
            f"| {sp['tokens_per_dispatch']} | {sp['wall_s']} |",
            "",
            f"**Tokens/dispatch: {sp['tokens_per_dispatch']}** at a "
            f"measured acceptance rate of {sp['acceptance_rate']} "
            f"({sp['accepted']}/{sp['proposed']} drafted tokens "
            f"accepted), {sp['speedup']}x wall vs one-token-per-"
            "dispatch on the same pool (slots=1 so batching stays out "
            "of the quotient). Greedy-exact acceptance: the served "
            "stream is the pure greedy stream either way — a rejected "
            "draft costs wasted compute, never a changed token.",
        ]
    fl = payload.get("fleet")
    if fl:
        k = fl.get("kill") or {}
        ttft = fl.get("ttft_s") or {}
        lat = fl.get("latency_s") or {}
        lines += [
            "",
            "## Serving fleet: failover under SIGKILL "
            "(serve_fleet.py router)",
            "",
            "| replicas | slots x chunk | requests | killed | failed "
            "| failovers | wall (s) | tokens/s |",
            "|---|---|---|---|---|---|---|---|",
            f"| {fl['replicas']} | {fl['slots']} x {fl['chunk']} "
            f"| {fl['workload']['requests']} | {k.get('victim')} "
            f"(after {k.get('after_done')} done) "
            f"| **{fl['failed_requests']}** | {fl['failovers']} "
            f"| {fl['wall_s']} | {fl['tokens_per_s']} |",
            "",
            f"Fleet TTFT p50/p95 = {ttft.get('p50')}/{ttft.get('p95')} s, "
            f"latency p50/p95 = {lat.get('p50')}/{lat.get('p95')} s, from "
            "the merged router+replica journals (`obs_report --fleet` — "
            "router submit to serving-replica completion, queue wait and "
            "failover latency included). The busiest replica is SIGKILLed "
            "mid-decode; its in-flight requests re-admit to healthy "
            f"replicas ({fl['reroutes']} re-routes) and the dead one "
            "relaunches under the restart budget. **failed = "
            f"{fl['failed_requests']}** is the zero-loss contract measured "
            "(the RUN_SLOW fault-injection test additionally pins every "
            "stream — re-served ones included — token-identical to "
            "in-process decode). Replicas are CPU subprocesses regardless "
            "of the bench host: this row is a routing/failover property, "
            "not a model-speed claim.",
        ]
    dg = payload.get("disagg")
    if dg:
        d, h = dg["disagg"], dg["homogeneous"]
        dt = d.get("ttft_s") or {}
        ht = h.get("ttft_s") or {}
        lines += [
            "",
            "## Disaggregated prefill/decode fleet: equal-replica A/B "
            "(serve_fleet.py roles, round 23)",
            "",
            f"{dg['workload']['requests']} requests, mixed workload — "
            f"{dg['workload']['mix']} — over {dg['replicas']} replicas "
            f"(role-tuned slots={dg['slots']} vs homogeneous "
            f"{dg.get('homog_slots')}, chunk={dg['chunk']}, "
            f"arrival gap {dg.get('arrival_gap_s')} s, migrate_threshold="
            f"{dg.get('migrate_threshold')}, seed={dg['seed']}), same "
            "paged-KV geometry both sides.",
            "",
            "| fleet | roles | done | failed | migrated | TTFT p50/p95 "
            "(s) | latency p95 (s) | tokens/s | KV wire B/req |",
            "|---|---|---|---|---|---|---|---|---|",
            f"| disagg | 2 prefill + 2 decode | {d['done']} "
            f"| {d['failed_requests']} | {d['migrated']} "
            f"| {dt.get('p50')}/{dt.get('p95')} "
            f"| {(d.get('latency_s') or {}).get('p95')} "
            f"| {d['tokens_per_s']} "
            f"| {d.get('kv_migration_bytes_per_req')} |",
            f"| homogeneous | 4 both | {h['done']} "
            f"| {h['failed_requests']} | {h['migrated']} "
            f"| {ht.get('p50')}/{ht.get('p95')} "
            f"| {(h.get('latency_s') or {}).get('p95')} "
            f"| {h['tokens_per_s']} | - |",
            "",
            f"**TTFT p95 speedup {dg['ttft_p95_speedup']}x, tokens/s "
            f"speedup {dg['tokens_per_s_speedup']}x** for the role-split "
            "fleet at EQUAL total replicas: chat decoders never stall "
            "behind a stranger's long prefill, and decode batches stay "
            "dense. The workload is STREAMED — continuous arrivals are "
            "the scenario role-splitting exists for (a single burst "
            "admits in one wave and levels the field); the config is "
            "role-tuned (denser decode slots, short prompts skip "
            "migration via `migrate_threshold`), which roles make safe "
            "to do. Migrated-request TTFT is measured conservatively "
            "(decode-leg first continuation token — the prefill leg's "
            "true first token lands earlier), so the disagg win is "
            "understated. Replicas are CPU subprocesses: a "
            "routing-topology property, not a model-speed claim; rerun "
            "`--disagg --write-docs` on the chip for the TPU row.",
        ]
    lg = payload.get("load_gen")
    if lg:
        dev = lg.get("device", "?")
        lines += [
            "",
            "## Overload robustness (load_gen scenarios, round 21)",
            "",
            f"slots={lg['slots']}, chunk={lg['chunk']}, seed={lg['seed']}"
            f", measured on {dev}. TTFT = submit → admission (the "
            "scheduler observable).",
        ]
        for scenario, sc in sorted(lg["scenarios"].items()):
            lines += [
                "",
                f"### `{scenario}` — {sc['n']} requests at "
                f"{sc['rate_rps']} rps offered"
                + (
                    f", queue_limit={sc['queue_limit']}"
                    if sc.get("queue_limit")
                    else ""
                ),
                "",
                "| class | requests | done | shed | shed rate "
                "| TTFT p50/p95 (s) | latency p50/p95 (s) |",
                "|---|---|---|---|---|---|---|",
            ]
            for prio, c in sorted(
                sc["classes"].items(), key=lambda kv: int(kv[0])
            ):
                t, l = c.get("ttft_s") or {}, c.get("latency_s") or {}
                lines.append(
                    f"| p{prio} | {c['requests']} | {c['done']} "
                    f"| {c['shed']} | {c['shed_rate']} "
                    f"| {t.get('p50')}/{t.get('p95')} "
                    f"| {l.get('p50')}/{l.get('p95')} |"
                )
            lines += [
                "",
                f"wall {sc['wall_s']} s; {sc['rejected']} QueueFull "
                "rejections (round-16 backpressure on same-or-lower-"
                "class arrivals); **hi-class misses: "
                f"{sc['hi_class_misses']}** (must be 0); sheds on "
                "lowest class only: "
                f"**{sc['sheds_on_lowest_class_only']}**.",
            ]
        lines += [
            "",
            "Under ≥2x-capacity overload the deadline/priority scheduler "
            "(serve.py round 21) sheds ONLY the batch class — loudly, as "
            "terminal `RequestShed` with a `request_shed` journal event — "
            "while every deadline-capable interactive/standard request "
            "completes. The per-class `fleet_ttft_p95_p{k}_s` and "
            "`shed_rate_p{k}` series feed the regression gate (both fail "
            "HIGH).",
        ]
    pc = payload.get("request_percentiles")
    if pc:
        lines += [
            "",
            "## Per-request latency percentiles (SLO view, "
            f"slots={pc['slots']}, chunk={pc['chunk']})",
            "",
            "| percentile | TTFT (s) | latency (s) |",
            "|---|---|---|",
        ]
        for p in ("p50", "p95", "p99"):
            lines.append(
                f"| {p} | {pc['ttft_s'][p]} | {pc['latency_s'][p]} |"
            )
        lines += [
            "",
            f"Measured over {pc['requests']} requests via the journal's "
            "trace reconstruction (`obs_report --requests` on the run's "
            "events.jsonl — the same path an operator uses on a "
            "production journal), on a separate journal-attached run so "
            "the headline rows above keep their journal-free "
            "methodology. TTFT includes queue wait: at "
            f"slots={pc['slots']} a workload of "
            f"{payload['workload']['requests']} requests queues, so the "
            "tail percentiles are an admission-control observable, not "
            "a pure model-speed one.",
        ]
    return "\n".join(lines)


def _docs_root() -> str:
    return os.path.abspath(
        os.path.join(
            os.path.dirname(__file__), "..", "..", "docs", "benchmarks"
        )
    )


def write_docs(payload: dict, root: str | None = None) -> None:
    root = root or _docs_root()
    with open(os.path.join(root, "serving.json"), "w") as f:
        json.dump(payload, f, indent=1)
    with open(os.path.join(root, "serving.md"), "w") as f:
        f.write(
            "# LM serving engine (serve.py): measured record\n\n"
            "Generated by `python -m distributed_tensorflow_tpu.tools."
            f"serve_bench --write-docs` on **{payload['device']}** "
            "(rerun on the v5e chip to refresh the on-chip record; "
            "tests/test_serve.py fails if this file drifts from "
            "serving.json). Timing is wall-clock around "
            "served workloads; every chunk ends in a D2H token fetch, so "
            "the numbers are dispatch-inclusive and barrier-honest "
            "(CLAUDE.md timing traps). Model: "
            f"d={payload['model']['model_dim']}, "
            f"{payload['model']['num_layers']} layers, vocab "
            f"{payload['model']['vocab']}.\n\n"
            + render(payload)
            + "\n\nReading it: chunking amortizes the per-dispatch fixed "
            "cost C (on the tunneled TPU a ~100 ms host round-trip; on "
            "CPU the ~2 ms dispatch+fetch overhead) over k tokens: "
            "per-token cost approaches the marginal t as k grows, with "
            "diminishing returns once C/(k·t) « 1. The scheduler admits "
            "at chunk boundaries, so k also bounds admission latency — "
            "pick the smallest k whose per-token cost sits on the flat "
            "part of the sweep. Batching rides the decode's "
            "parameter-read-bound step: on an accelerator 8 slots cost "
            "barely more HBM traffic per step than 1 (params dominate at "
            "serving widths), so 8 streams multiply tokens/s; a CPU run "
            "of this bench pays batch compute linearly and shows ~1x "
            "there — the slots lever is an accelerator phenomenon, the "
            "chunk lever shows everywhere (and both multiply through the "
            "~100 ms tunnel round-trip on the chip of record).\n\n"
            "Provenance (the round-9 TUNNEL-TPU convention): every row "
            f"in this file was measured on **{payload['device']}**"
            + (
                " — i.e. NOT yet on the chip of record. The slot-density "
                "row is a geometry + admission-control property and "
                "carries over as-is; the batched-speedup (≥5x slots), "
                "chunk (≥10x), and speculation wall-clock rows are "
                "TUNNEL-TPU claims — the ~100 ms round-trip multiplies "
                "every per-dispatch saving, so CPU numbers UNDERSTATE "
                "them (tokens/dispatch and the acceptance rate carry "
                "over; wall speedups do not). Rerun `python -m "
                "distributed_tensorflow_tpu.tools.serve_bench "
                "--write-docs` on the v5e to refresh."
                if payload["device"] == "cpu"
                else " (the chip of record)."
            )
            + "\n"
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=96)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=32)
    ap.add_argument("--write-docs", action="store_true")
    ap.add_argument(
        "--events",
        default=None,
        help="append the measured points as bench_point journal events "
        "(default with --write-docs: docs/benchmarks/events.jsonl)",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="run ONLY the fleet failover bench (subprocess replicas + "
        "one SIGKILL) and merge its row into the committed serving.json "
        "— the other rows are untouched, so a fleet refresh needs no "
        "chip and no full rerun",
    )
    ap.add_argument(
        "--load-gen",
        action="store_true",
        help="run ONLY the overload load-generator scenarios "
        "(tools/load_gen.py against an in-process TextServer) and merge "
        "the section into the committed serving.json (the --fleet merge "
        "pattern) — per-class TTFT/shed-rate series feed the gate",
    )
    ap.add_argument(
        "--disagg",
        action="store_true",
        help="run ONLY the disaggregated prefill/decode A/B (role-split "
        "vs homogeneous subprocess fleets at equal total replicas on the "
        "same mixed workload) and merge its section into the committed "
        "serving.json (the --fleet merge pattern) — TTFT/tokens-per-s/"
        "migration-bytes series feed the gate",
    )
    ap.add_argument(
        "--decode-engine",
        action="store_true",
        help="run ONLY the fused-vs-XLA decode engine A/B and merge its "
        "section into the committed serving.json (the --fleet merge "
        "pattern); on the chip this fills the pallas rows, off-chip it "
        "measures the xla rows and records the pallas ones as pending",
    )
    ap.add_argument(
        "--decode-dispatches",
        action="store_true",
        help="re-count ONLY the dispatches-per-token half of the decode "
        "A/B (traced jaxpr, device-independent) and merge it under the "
        "committed decode_engine section — the timing rows (each a "
        "single committed point per series) are untouched",
    )
    args = ap.parse_args(argv)
    events_path = args.events
    if events_path is None and args.write_docs:
        events_path = os.path.join(_docs_root(), "events.jsonl")
    if args.decode_dispatches:
        disp = bench_decode_dispatches()
        with open(os.path.join(_docs_root(), "serving.json")) as f:
            payload = json.load(f)
        payload.setdefault("decode_engine", {})["dispatches"] = disp
        print(json.dumps(disp))
        if args.write_docs:
            write_docs(payload)
            print(f"wrote {_docs_root()}/serving.md and serving.json")
        else:
            print(render(payload))
        if events_path:
            n = len(emit_dispatch_events(payload, events_path))
            print(f"appended {n} bench_point events to {events_path}")
        return 0
    if args.decode_engine:
        de = bench_decode_engine()
        with open(os.path.join(_docs_root(), "serving.json")) as f:
            payload = json.load(f)
        # A timing rerun (chip or cpu) never re-traces the dispatch
        # half — carry the committed counts forward (the --fleet merge
        # pattern, one level down).
        prev = payload.get("decode_engine") or {}
        if "dispatches" in prev:
            de.setdefault("dispatches", prev["dispatches"])
        payload["decode_engine"] = de
        print(json.dumps(de))
        if args.write_docs:
            write_docs(payload)
            print(f"wrote {_docs_root()}/serving.md and serving.json")
        else:
            print(render(payload))
        if events_path:
            n = len(emit_decode_events(payload, events_path))
            print(f"appended {n} bench_point events to {events_path}")
        return 0
    if args.load_gen:
        lg = bench_load_gen()
        with open(os.path.join(_docs_root(), "serving.json")) as f:
            payload = json.load(f)
        payload["load_gen"] = lg
        print(json.dumps(lg))
        if args.write_docs:
            write_docs(payload)
            print(f"wrote {_docs_root()}/serving.md and serving.json")
        else:
            print(render(payload))
        if events_path:
            n = len(emit_load_gen_events(payload, events_path))
            print(f"appended {n} bench_point events to {events_path}")
        return 0
    if args.disagg:
        dg = bench_disagg()
        with open(os.path.join(_docs_root(), "serving.json")) as f:
            payload = json.load(f)
        payload["disagg"] = dg
        print(json.dumps(dg))
        if args.write_docs:
            write_docs(payload)
            print(f"wrote {_docs_root()}/serving.md and serving.json")
        else:
            print(render(payload))
        if events_path:
            n = len(emit_disagg_events(payload, events_path))
            print(f"appended {n} bench_point events to {events_path}")
        return 0
    if args.fleet:
        fleet = bench_fleet()
        with open(os.path.join(_docs_root(), "serving.json")) as f:
            payload = json.load(f)
        payload["fleet"] = fleet
        print(json.dumps(fleet))
        if args.write_docs:
            write_docs(payload)
            print(f"wrote {_docs_root()}/serving.md and serving.json")
        else:
            print(render(payload))
        if events_path:
            n = len(emit_fleet_events(payload, events_path))
            print(f"appended {n} bench_point events to {events_path}")
        return 0
    payload = bench(
        n_requests=args.requests,
        max_new=args.max_new,
        slots=args.slots,
        chunk=args.chunk,
    )
    # A full rerun re-measures every engine row but not the fleet row
    # (subprocess bench, its own --fleet entry point) or the decode
    # engine A/B (its own --decode-engine entry point): carry the
    # committed sections forward instead of silently dropping them.
    try:
        with open(os.path.join(_docs_root(), "serving.json")) as f:
            old = json.load(f)
        for key in ("fleet", "decode_engine", "load_gen", "disagg"):
            if key in old:
                payload.setdefault(key, old[key])
    except (OSError, ValueError):
        pass
    print(json.dumps(payload))
    if args.write_docs:
        write_docs(payload)
        print(f"wrote {_docs_root()}/serving.md and serving.json")
    else:
        print(render(payload))
    if events_path:
        n = len(emit_bench_events(payload, events_path))
        print(f"appended {n} bench_point events to {events_path}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
