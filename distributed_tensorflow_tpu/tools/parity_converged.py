"""Converged parity grid: the reference's experiment table, run to completion.

The round-1 benchmark grid ran 3 epochs and printed 0.10-0.14 accuracies next
to the reference's converged 0.72/0.80/0.82 — demonstrating throughput while
validating none of the convergence findings it cited. This tool runs the
accuracy leg to convergence (default 100 epochs, the reference's count —
reference tfsingle.py:10) under the reference's own epoch convention
(``per_worker_epoch``: each worker passes over the full dataset per epoch,
reference tfdist_between.py:87), reproducing the README's qualitative
findings as checkable orderings:

- sync N-worker ≈ single-device  (reference README.md:143-150 — sync
  averaging makes N workers one effective update stream: 0.72 vs 0.72);
- async > sync at equal workers  (README.md:66-74 — async's N× update
  count: 0.80 vs 0.72);
- async 3-worker > async 2-worker (README.md:231-254 — more workers →
  more updates → higher accuracy: 0.82-0.83 vs 0.80).

Absolute accuracies differ from the reference's (synthetic deterministic
MNIST, JAX PRNG init — SURVEY.md §7 hard-part b sanctions matching the
distribution, not bits; the oracle analog of the reference's 0.72 is 0.816
on this data) but the orderings are the reference's findings and are what
``tests/integration/test_oracles.py`` asserts.

Every row uses the whole-run compiled path (train/compiled_run.py) so a
100-epoch leg is one dispatch. Usage::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m distributed_tensorflow_tpu.tools.parity_converged \
        --epochs 100 --markdown docs/benchmarks/parity_converged.md
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# jax-backed imports live inside build_trainer/run_grid (lean-import
# convention, round 8/9): the bench_point emission half of this module
# (emit_bench_events — the round-14 regression-gate wiring for the
# paper-parity margins) must import on degraded containers whose jax
# lacks the mesh APIs the grid itself needs.


def _silent(*a, **k):
    pass


def _rows(n_devices: int):
    """(name, workers, sync?, reference row + converged accuracy)."""
    rows = [("single", 1, True, "ref #1 tfsingle.py (0.72)")]
    if n_devices >= 2:
        rows.append(("sync-2-pw", 2, True, "ref #5 tfdist_between_sync.py (0.72 = single)"))
        rows.append(("async-2-pw", 2, False, "ref #3 tfdist_between.py (0.80 > sync)"))
    if n_devices >= 3:
        rows.append(("async-3-pw", 3, False, "ref #9 3-worker async (0.82-0.83 > 2-worker)"))
    return rows


def build_trainer(name: str, workers: int, sync: bool, epochs: int, datasets):
    """One parity row's Trainer: reference hyperparameters, reference epoch
    convention, whole-run compiled. Async rows use the default
    ``update_scale=N``: the reference PS applied all N workers' updates
    *sequentially* to one parameter set (N×550 applies per epoch moved the
    params N× as far, reference README.md:66-72), while the local-SGD
    emulation averages N copies — which moves the mean only ~1×. Scaling
    each local update by N restores the PS's per-epoch parameter movement
    (SURVEY.md §2b sanctions update-count matching); measured: with
    update_scale=1 every async row converges exactly like sync, with
    update_scale=N the reference's orderings reappear."""
    import jax

    from distributed_tensorflow_tpu.config import TrainConfig
    from distributed_tensorflow_tpu.models import MLP
    from distributed_tensorflow_tpu.parallel.mesh import make_mesh
    from distributed_tensorflow_tpu.parallel.strategy import (
        AsyncDataParallel,
        SingleDevice,
        SyncDataParallel,
    )
    from distributed_tensorflow_tpu.train import Trainer

    cfg = TrainConfig(
        epochs=epochs,
        compiled_run=True,
        per_worker_epoch=(name != "single"),
        log_frequency=10**9,
        logs_path="",
    )
    if name == "single":
        strategy = SingleDevice()
    else:
        mesh = make_mesh((workers, 1), devices=jax.devices()[:workers])
        if sync:
            strategy = SyncDataParallel(mesh)
        else:
            strategy = AsyncDataParallel(mesh, avg_every=50)
    return Trainer(MLP(), datasets, cfg, strategy=strategy, print_fn=_silent)


def run_grid(epochs: int = 100, datasets=None, print_fn=print) -> list[dict]:
    import jax

    if datasets is None:
        from distributed_tensorflow_tpu.data import read_data_sets

        datasets = read_data_sets("MNIST_data", one_hot=True)
    results = []
    for name, workers, sync, ref in _rows(len(jax.devices())):
        t0 = time.time()
        tr = build_trainer(name, workers, sync, epochs, datasets)
        res = tr.run()
        results.append(
            {
                "row": name,
                "workers": workers,
                "device": jax.devices()[0].device_kind,
                "epochs": epochs,
                "final_accuracy": round(res["accuracy"], 4),
                "final_cost": round(res["final_cost"], 4),
                "global_step": res["global_step"],
                "wall_s": round(time.time() - t0, 1),
                "reference": ref,
            }
        )
        print_fn(f"{name}: acc={res['accuracy']:.4f} ({time.time() - t0:.0f}s)")
    return results


def check_orderings(results: list[dict]) -> list[str]:
    """The reference README's findings as explicit pass/fail claims."""
    acc = {r["row"]: r["final_accuracy"] for r in results}
    checks = []
    if "sync-2-pw" in acc:
        ok = abs(acc["sync-2-pw"] - acc["single"]) < 0.05
        checks.append(
            f"{'PASS' if ok else 'FAIL'} sync-2 ≈ single "
            f"({acc['sync-2-pw']:.4f} vs {acc['single']:.4f}; README.md:143-150)"
        )
    if "async-2-pw" in acc and "sync-2-pw" in acc:
        ok = acc["async-2-pw"] > acc["sync-2-pw"]
        checks.append(
            f"{'PASS' if ok else 'FAIL'} async-2 > sync-2 "
            f"({acc['async-2-pw']:.4f} vs {acc['sync-2-pw']:.4f}; README.md:66-74)"
        )
    if "async-3-pw" in acc and "async-2-pw" in acc:
        ok = acc["async-3-pw"] > acc["async-2-pw"]
        checks.append(
            f"{'PASS' if ok else 'FAIL'} async-3 > async-2 "
            f"({acc['async-3-pw']:.4f} vs {acc['async-2-pw']:.4f}; README.md:231-254)"
        )
    return checks


def oracle_margins(results: list[dict]) -> dict:
    """The experiment table's findings as NUMBERS (not just orderings):
    per-row converged accuracy plus the two margins the reference's
    claims rest on — async-over-sync at equal workers, and
    more-async-workers-is-better. One place computes them so the
    PASS/FAIL checks, the bench_point events, and any future table stay
    on the same definitions."""
    acc = {r["row"]: r["final_accuracy"] for r in results}
    out = {f"{row}_acc": v for row, v in acc.items()}
    if "async-2-pw" in acc and "sync-2-pw" in acc:
        out["async2_minus_sync2"] = round(
            acc["async-2-pw"] - acc["sync-2-pw"], 4
        )
    if "async-3-pw" in acc and "async-2-pw" in acc:
        out["async3_minus_async2"] = round(
            acc["async-3-pw"] - acc["async-2-pw"], 4
        )
    return out


def emit_bench_events(results: list[dict], events_path: str) -> int:
    """The paper-parity oracle margins as ``bench_point`` journal events
    (round 14): the round-12 regression gate then guards the PARITY
    trajectory — a change that shrinks the async-over-sync margin fails
    the fast tier the same way an eroded throughput number does.
    Accuracy units are not ms/s, so the gate's direction rule fails LOW
    (a higher accuracy or wider margin is never a regression). Series
    identity is (parity_converged, <name>, device): a chip rerun starts
    its own series. Rows re-emitted from a committed grid json
    (``--from-json``) carry the json's device — every historical grid
    ran on the 8-virtual-CPU harness, so rows without the key are
    "cpu"."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    device = results[0].get("device") if results else None
    if device is None:
        import jax

        device = jax.devices()[0].device_kind
    epochs = results[0]["epochs"] if results else None
    j = EventJournal(events_path, run_id="parity_converged")
    n = 0
    try:
        for name, value in oracle_margins(results).items():
            j.emit(
                "bench_point",
                tool="parity_converged",
                name=name,
                value=float(value),
                unit="acc",
                device=device,
                epochs=epochs,
            )
            n += 1
    finally:
        j.close()
    return n


def markdown(results: list[dict], checks: list[str]) -> str:
    lines = [
        "| Row | Workers | Epochs | Final accuracy | Final cost | Global step | Reference counterpart |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in results:
        lines.append(
            "| %s | %d | %d | %.4f | %.4f | %d | %s |"
            % (
                r["row"],
                r["workers"],
                r["epochs"],
                r["final_accuracy"],
                r["final_cost"],
                r["global_step"],
                r["reference"],
            )
        )
    lines.append("")
    lines.append("Reference-finding checks:")
    lines.extend(f"- {c}" for c in checks)
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--epochs", type=int, default=100)
    p.add_argument("--json", type=str, default=None)
    p.add_argument("--markdown", type=str, default=None)
    p.add_argument(
        "--events",
        default=None,
        help="append the oracle margins as bench_point journal events "
        "(docs/benchmarks/events.jsonl to feed the regression gate — "
        "only meaningful for full-length runs: the margins are "
        "epoch-count-dependent and the events carry the count)",
    )
    p.add_argument(
        "--from-json",
        default=None,
        help="no measurement: load a committed grid json (--json output) "
        "and emit its margins as bench_point events to --events — runs "
        "anywhere, no mesh (the lm_phase_bench --recompute-docs "
        "pattern); rows without a device key are tagged cpu (every "
        "historical grid ran on the 8-virtual-CPU harness)",
    )
    args = p.parse_args(argv)
    if args.from_json:
        if not args.events:
            p.error("--from-json needs --events (the journal to append to)")
        with open(args.from_json) as f:
            payload = json.load(f)
        rows = [dict(r, device=r.get("device", "cpu")) for r in payload["rows"]]
        n = emit_bench_events(rows, args.events)
        print(f"appended {n} bench_point events to {args.events}")
        return 0
    results = run_grid(
        epochs=args.epochs, print_fn=lambda *a: print(*a, file=sys.stderr)
    )
    checks = check_orderings(results)
    out = markdown(results, checks)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"rows": results, "checks": checks}, f, indent=2)
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(out)
    if args.events:
        n = emit_bench_events(results, args.events)
        print(
            f"appended {n} bench_point events to {args.events}",
            file=sys.stderr,
        )
    return 0 if all(c.startswith("PASS") for c in checks) else 1


if __name__ == "__main__":
    raise SystemExit(main())
