"""Analytical cost/roofline report for a compiled train step.

The reference reasoned about performance by wall-clock alone — hand-rolled
``AvgTime`` per 100 batches and per-epoch totals pasted into its experiment
log (reference tfdist_between.py:98-110, README.md:38-40,97-101), with no
way to say *why* a configuration was slow. On TPU the compiler itself can
answer that: XLA's analytical model reports FLOPs and bytes accessed for
any compiled program, and comparing their ratio (arithmetic intensity)
against the hardware's FLOPs/byte balance point classifies the program as
compute- or bandwidth-bound and predicts its per-step floor — the
"How to Scale Your Model" roofline recipe, as a tool.

Usage::

    python -m distributed_tensorflow_tpu.tools.cost_analysis --model mlp
    python -m distributed_tensorflow_tpu.tools.cost_analysis --model lstm --batch 512

or ``cost_analysis.analyze(model, batch_size=...)`` in code. Numbers come
from ``jax.stages.Compiled.cost_analysis()`` — the same estimates the XLA
scheduler uses; they are analytical (no execution, works on any backend),
so use them for *shape* questions (bound class, scaling with batch) and
the benchmark tools for measured wall clock.
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.ops import cross_entropy, sgd
from distributed_tensorflow_tpu.parallel.strategy import SingleDevice

# Peak numbers for rooflining, per chip. Sources: public TPU spec sheets
# (bf16 matmul peak / HBM bandwidth). "cpu" is a rough placeholder so the
# tool classifies in CPU test environments.
CHIP_PEAKS = {
    "tpu v5 lite": {"flops": 197e12, "hbm_bytes_per_s": 819e9},
    "tpu v4": {"flops": 275e12, "hbm_bytes_per_s": 1228e9},
    "cpu": {"flops": 1e11, "hbm_bytes_per_s": 5e10},
}


def measured_ceiling_tflops() -> float | None:
    """The MEASURED bf16 ceiling from the committed roofline record
    (docs/benchmarks/roofline_tpu.json), or None. Every MFU*-style column
    must divide by THIS, not a hardcoded constant — a roofline re-measure
    has to propagate to every committed table or the records silently mix
    denominators (round-5 review finding)."""
    import json as _json
    import os as _os

    path = _os.path.join(
        _os.path.dirname(__file__), "..", "..", "docs", "benchmarks",
        "roofline_tpu.json",
    )
    try:
        with open(path) as f:
            return _json.load(f).get("ceiling_bf16_tflops")
    except Exception:
        return None


def _chip_peaks(device) -> dict | None:
    """Peaks for the device, or None when unknown — a wrong balance point
    misclassifies every program, so refuse rather than guess."""
    kind = device.device_kind.lower()
    for prefix, peaks in CHIP_PEAKS.items():
        if kind.startswith(prefix):
            return peaks
    return None


def _roofline(compiled, batch_size: int, device) -> dict:
    """Shared report body: XLA's analytical FLOPs/bytes for a compiled
    step, arithmetic intensity, and (when the chip's peaks are known) the
    balance-point classification and per-step floor — used verbatim by the
    classifier and LM analyzers so the two can't drift."""
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    intensity = flops / bytes_accessed if bytes_accessed else float("inf")
    report = {
        "flops_per_step": flops,
        "bytes_per_step": bytes_accessed,
        "arithmetic_intensity_flops_per_byte": round(intensity, 3),
    }
    peaks = _chip_peaks(device)
    if peaks is None:
        report.update(
            chip_balance_flops_per_byte=None, bound="unknown",
            roofline_floor_us=None, examples_per_sec_roofline=None,
        )
        return report
    balance = peaks["flops"] / peaks["hbm_bytes_per_s"]  # FLOPs/byte
    t_compute = flops / peaks["flops"]
    t_memory = bytes_accessed / peaks["hbm_bytes_per_s"]
    report.update(
        chip_balance_flops_per_byte=round(balance, 1),
        bound="compute" if intensity > balance else "memory",
        roofline_floor_us=round(max(t_compute, t_memory) * 1e6, 3),
        examples_per_sec_roofline=round(
            batch_size / max(t_compute, t_memory, 1e-12), 1
        ),
    )
    return report


def analyze(
    model,
    batch_size: int = 100,
    in_dim: int = 784,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    device=None,
) -> dict:
    """Compile one SGD train step for ``model`` and report its analytical
    cost plus the roofline classification on ``device`` (default: device 0).
    """
    device = device or jax.devices()[0]
    # Analyze the *actual* program the Trainer compiles — the SingleDevice
    # strategy's train step (parallel/strategy.py) — not a re-derivation
    # that could drift from it.
    strategy = SingleDevice()
    opt = sgd(learning_rate)
    state = strategy.init_state(model, opt, seed=1)
    step = strategy.make_train_step(model, cross_entropy, opt)

    x = jnp.zeros((batch_size, in_dim), jnp.float32)
    y = jnp.zeros((batch_size, out_dim), jnp.float32)
    compiled = step.lower(state, x, y).compile()
    n_params = sum(
        p.size for p in jax.tree_util.tree_leaves(state.params)
    )
    mem = compiled.memory_analysis()
    report = {
        "model": type(model).__name__,
        "batch_size": batch_size,
        "device_kind": device.device_kind,
        "param_count": int(n_params),
        "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
    }
    report.update(_roofline(compiled, batch_size, device))
    return report


def analyze_lm(
    model,
    batch_size: int = 8,
    *,
    optimizer=None,
    device=None,
) -> dict:
    """Roofline for one LM training step (``make_lm_train_step`` — the
    actual program `LMTrainer`/`tools/lm_bench.py` run, not a
    re-derivation): compiled FLOPs/bytes, arithmetic intensity vs the
    chip's balance point, per-step floor, and the FLOPs count
    ``tools/lm_bench.py`` divides by measured step time for MFU."""
    from distributed_tensorflow_tpu.models.gpt import make_lm_train_step
    from distributed_tensorflow_tpu.ops import optim as optim_lib

    device = device or jax.devices()[0]
    optimizer = optimizer or optim_lib.make("adam", 1e-3)
    params = model.init(seed=1)
    opt_state = optimizer.init(params)
    step = make_lm_train_step(model, optimizer)
    tokens = jnp.zeros((batch_size, model.max_len), jnp.int32)
    compiled = step.lower(params, opt_state, tokens).compile()
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    report = {
        "model": "GPTLM",
        "batch_size": batch_size,
        "seq_len": model.max_len,
        "tokens_per_step": batch_size * model.max_len,
        "device_kind": device.device_kind,
        "param_count": int(n_params),
    }
    report.update(_roofline(compiled, batch_size, device))
    return report


def format_report(r: dict) -> str:
    lines = [
        f"{r['model']} @ batch {r['batch_size']} on {r['device_kind']}",
        f"  params:               {r['param_count']:,}",
        f"  flops/step:           {r['flops_per_step']:,.0f}",
        f"  bytes/step:           {r['bytes_per_step']:,.0f}",
        f"  arithmetic intensity: {r['arithmetic_intensity_flops_per_byte']} FLOP/B",
    ]
    if r["bound"] == "unknown":
        lines.append(
            "  bound:                unknown (no peak numbers for this chip"
            " — add them to CHIP_PEAKS)"
        )
    else:
        lines += [
            f"  chip balance:         {r['chip_balance_flops_per_byte']} FLOP/B",
            f"  bound:                {r['bound']}",
            f"  roofline floor:       {r['roofline_floor_us']} us/step"
            f"  ({r['examples_per_sec_roofline']:,.0f} ex/s)",
        ]
    return "\n".join(lines)


def main(argv=None) -> int:
    from distributed_tensorflow_tpu.models import MODEL_REGISTRY, build_model

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "--model", default="mlp", choices=sorted(MODEL_REGISTRY) + ["lm"]
    )
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--seq-len", type=int, default=512, help="lm only")
    p.add_argument("--model-dim", type=int, default=256, help="lm only")
    p.add_argument("--layers", type=int, default=4, help="lm only")
    p.add_argument("--json", action="store_true", help="emit JSON instead of text")
    args = p.parse_args(argv)
    if args.model == "lm":
        from distributed_tensorflow_tpu.models.gpt import GPTLM

        report = analyze_lm(
            GPTLM(
                vocab_size=8192,
                max_len=args.seq_len,
                model_dim=args.model_dim,
                num_heads=max(1, args.model_dim // 64),
                num_layers=args.layers,
            ),
            batch_size=args.batch,
        )
    else:
        report = analyze(build_model(args.model), batch_size=args.batch)
    if args.json:
        print(json.dumps(report))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
