"""Device occupancy snapshot — the reference's ``nvidia-smi`` check, TPU-native.

The reference verified GPU residency and memory pressure by pasting
``nvidia-smi`` snapshots into its experiment log (reference
README.md:76-86,103-113,152-162; SURVEY.md §4 item 3) — e.g. confirming two
worker processes shared gpu0's memory under ``allow_growth``. TPUs have no
nvidia-smi; the equivalents are the PJRT device list and per-device memory
statistics, plus the live on-device arrays JAX is tracking.

Usage::

    python -m distributed_tensorflow_tpu.tools.device_info

or ``device_info.snapshot()`` in code (returns the rows it prints).
"""

from __future__ import annotations

import jax


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024:
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


def snapshot(print_fn=print) -> list[dict]:
    rows = []
    live = list(jax.live_arrays())
    for dev in jax.local_devices():
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except (NotImplementedError, jax.errors.JaxRuntimeError):
            pass  # CPU/interpret backends expose no allocator stats
        arrays_here = [a for a in live if dev in getattr(a, "devices", lambda: set())()]
        rows.append(
            {
                "id": dev.id,
                "process": dev.process_index,
                "platform": dev.platform,
                "kind": dev.device_kind,
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
                "live_arrays": len(arrays_here),
                "live_array_bytes": sum(a.nbytes for a in arrays_here),
            }
        )
    if print_fn is not None:
        print_fn(
            f"{'dev':>4} {'proc':>4} {'platform':>9} {'kind':>14} "
            f"{'in_use':>10} {'peak':>10} {'limit':>10} {'arrays':>7} {'array_B':>10}"
        )
        for r in rows:
            print_fn(
                f"{r['id']:>4} {r['process']:>4} {r['platform']:>9} {r['kind'][:14]:>14} "
                f"{_fmt_bytes(r['bytes_in_use']):>10} {_fmt_bytes(r['peak_bytes_in_use']):>10} "
                f"{_fmt_bytes(r['bytes_limit']):>10} {r['live_arrays']:>7} "
                f"{_fmt_bytes(r['live_array_bytes']):>10}"
            )
    return rows


def main() -> int:
    snapshot()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
