"""DiLoCo vs sync-dp: held-out perplexity vs sync rounds / wall-clock.

The converged-parity discipline of ``tools/parity_converged.py`` (run the
claim to convergence, print PASS/FAIL orderings — not 3-epoch throughput
next to converged reference numbers) applied to ROADMAP item 5: the
paper's async-over-sync thesis in its modern communication-reducing form
(train/local_sgd.py). Every row trains the same GPT on the same synthetic
copy corpus with the same inner optimizer and GLOBAL batch; the rows
differ only in how often the gang synchronizes:

- ``sync-dp`` — gradient all-reduce every step (one sync round per
  step). On a mesh-capable jax this is the real ``dp`` mode with
  measured ``comm_stats`` journal events; on a degraded container it
  runs as the single-device program (bit-the-same math — GSPMD dp ==
  single-device on the global batch, proven repo-wide) with the rounds
  computed by the same ``sync_rounds_between`` arithmetic the trainer
  journals (engine column says which).
- ``diloco-hH`` — H inner steps per worker, ONE outer Nesterov update:
  H× fewer sync rounds per token, measured from the journal's
  ``comm_stats`` counters, never asserted.
- ``diloco-h8-int8[-stream]`` — round 17: the same gang with
  error-feedback int8 outer deltas (another ~4× bytes/token, per-tensor
  scales) and, for ``-stream``, the overlapped exchange (outer update
  applied one round late — streaming-DiLoCo). Payload bytes come from
  the grown ``comm_stats`` events; ``comm_bytes_per_token`` is
  gate-covered and fails HIGH.

The PASS/FAIL checks are the acceptance claims: DiLoCo at H ≥ 8 within
2% of sync-dp held-out perplexity at ≥ 4× fewer sync rounds. The
``outer_lr=N`` row reproduces the reference's ``update_scale=N``
sequential-apply convention for completeness (its convergence at toy
scale is aggressive, exactly like the async oracle's early epochs — the
paper-parity claims for that convention live in parity_converged).

Wall-clock on a CPU container reflects vectorization, not communication
— the dispatch-amortization half of the story (the outer round as the
dispatch unit over the ~100 ms tunnel) is a TUNNEL-TPU phenomenon;
rerun ``--write-docs`` on the chip (the verify-skill runbook has the
command). Usage::

    python -m distributed_tensorflow_tpu.tools.diloco_bench \
        --epochs 8 --write-docs
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _silent(*a, **k):
    pass


class _CaptureJournal:
    """List-capturing journal (duck-typed) for the per-row comm events."""

    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append({"kind": kind, **fields})
        return fields

    def flush(self):
        pass


def _model():
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.models.gpt import GPTLM

    return GPTLM(
        vocab_size=61,
        max_len=16,
        model_dim=32,
        num_heads=4,
        num_layers=2,
        compute_dtype=jnp.float32,
    )


def _corpus():
    from distributed_tensorflow_tpu.data import copy_corpus

    return copy_corpus(
        num=1664, half_len=8, vocab=61, n_val=128, n_test=128, seed=0
    )


def _mesh_or_none(workers: int):
    """A ``workers``-wide data mesh, or None on a degraded jax / small
    device count — the vmapped single-device gang engine then carries
    the same math (train/local_sgd.py)."""
    import jax

    if len(jax.devices()) < workers:
        return None
    try:
        from distributed_tensorflow_tpu.parallel import make_mesh

        return make_mesh(
            (workers,), ("data",), devices=jax.devices()[:workers]
        )
    except (ImportError, AttributeError):
        return None


def _rows(workers: int):
    """(name, sync_every | None for the dp baseline, outer kwargs —
    TrainConfig fields, so the round-17 levers ride through as config
    keys)."""
    return [
        ("sync-dp", None, {}),
        (
            "diloco-h8",
            8,
            dict(outer_lr=1.0, outer_momentum=0.9),
        ),
        (
            "diloco-h32",
            32,
            dict(outer_lr=1.0, outer_momentum=0.9),
        ),
        (
            "diloco-h8-lrN",
            8,
            # outer_lr=None → N: the reference PS sequential-apply
            # convention (update_scale=N); recorded, not gated.
            dict(outer_lr=None, outer_momentum=0.0),
        ),
        (
            # Round 17: error-feedback int8 outer deltas — another ~4×
            # bytes/token on top of H× (per-tensor scales; the residual
            # re-injects the rounding next round).
            "diloco-h8-int8",
            8,
            dict(outer_lr=1.0, outer_momentum=0.9, delta_dtype="int8"),
        ),
        (
            # + overlapped exchange: the outer update applies one round
            # late (streaming-DiLoCo), so a real gang's all-reduce hides
            # behind the next H inner steps. Outer momentum HALVED vs
            # the non-overlapped rows: the one-round delay compounds
            # momentum (μ=0.9 diverges under overlap; measured μ≈0.4-0.5
            # matches the non-overlapped row — local_sgd.OVERLAP_MERGE).
            "diloco-h8-int8-stream",
            8,
            dict(
                outer_lr=1.0,
                outer_momentum=0.4,
                delta_dtype="int8",
                delta_overlap=True,
            ),
        ),
    ]


def run_grid(
    epochs: int = 8, workers: int = 4, print_fn=print
) -> list[dict]:
    import jax

    from distributed_tensorflow_tpu.config import TrainConfig
    from distributed_tensorflow_tpu.train import LMTrainer
    from distributed_tensorflow_tpu.train.local_sgd import (
        params_nbytes,
        sync_rounds_between,
    )

    device = jax.devices()[0].device_kind
    mesh = _mesh_or_none(workers)
    pbytes = params_nbytes(
        jax.eval_shape(lambda: _model().init(seed=0))
    )
    batch_size = 64
    results = []
    for name, sync_every, outer_kw in _rows(workers):
        journal = _CaptureJournal()
        cfg_kw: dict = {}
        trainer_kw: dict = {"journal": journal}
        if sync_every is None:
            engine = "dp-mesh" if mesh is not None else "single(dp-math)"
            if mesh is not None:
                trainer_kw["mesh"] = mesh
        else:
            cfg_kw = dict(
                dp_mode="diloco", sync_every=sync_every, **outer_kw
            )
            if mesh is not None:
                engine = "diloco-mesh"
                trainer_kw["mesh"] = mesh
            else:
                engine = "diloco-vmapped"
                cfg_kw["diloco_workers"] = workers
        ds = _corpus()
        tr = LMTrainer(
            _model(),
            ds,
            TrainConfig(
                epochs=epochs,
                batch_size=batch_size,
                optimizer="adam",
                learning_rate=3e-3,
                log_frequency=10**9,
                logs_path="",
                scan_epoch=True,
                **cfg_kw,
            ),
            print_fn=_silent,
            **trainer_kw,
        )
        t0 = time.time()
        res = tr.run()
        wall = time.time() - t0
        comm = [
            e for e in journal.events if e["kind"] == "comm_stats"
        ]
        if comm:
            rounds = sum(e["sync_rounds"] for e in comm)
            nbytes = sum(e["allreduce_bytes"] for e in comm)
            payload = sum(
                e.get("payload_bytes", e["allreduce_bytes"]) for e in comm
            )
        else:
            # single(dp-math) engine: dp all-reduces every step — the
            # same arithmetic the trainer journals on a mesh.
            rounds = sync_rounds_between(0, res["global_step"], 1)
            nbytes = rounds * pbytes
            payload = nbytes
        # Wire bytes per trained token — the round-17 headline unit
        # (gate-covered, fails HIGH): payload ÷ (steps × global batch ×
        # sequence length), all counted — derived from the ACTUAL config
        # and corpus so a future shape change cannot silently skew the
        # gate's denominator.
        tokens = (
            int(res["global_step"])
            * batch_size
            * int(ds.train.tokens.shape[1])
        )
        results.append(
            {
                "row": name,
                "engine": engine,
                "device": device,
                "workers": workers,
                "epochs": epochs,
                "sync_every": sync_every or 1,
                "outer_lr": None
                if sync_every is None
                else (
                    "N"
                    if outer_kw["outer_lr"] is None
                    else outer_kw["outer_lr"]
                ),
                "outer_momentum": outer_kw.get("outer_momentum"),
                "delta_dtype": outer_kw.get("delta_dtype"),
                "overlap": bool(outer_kw.get("delta_overlap")),
                "perplexity": round(float(res["perplexity"]), 4),
                "steps": int(res["global_step"]),
                "sync_rounds": int(rounds),
                "allreduce_mb": round(nbytes / 1e6, 2),
                "payload_mb": round(payload / 1e6, 2),
                "bytes_per_token": round(payload / max(tokens, 1), 2),
                # One lax.scan dispatch per epoch: on the tunneled chip
                # the outer round rides inside it (docs/performance.md).
                "train_dispatches": int(epochs),
                "wall_s": round(wall, 1),
            }
        )
        print_fn(
            f"{name}: ppl={results[-1]['perplexity']} "
            f"rounds={rounds} ({wall:.0f}s, {engine})"
        )
    return results


def check_claims(results: list[dict]) -> list[str]:
    """The acceptance claims as explicit PASS/FAIL lines (the
    parity_converged discipline)."""
    by = {r["row"]: r for r in results}
    checks = []
    sync = by.get("sync-dp")
    d8 = by.get("diloco-h8")
    if sync and d8:
        red = sync["sync_rounds"] / max(d8["sync_rounds"], 1)
        ok = red >= 4.0
        checks.append(
            f"{'PASS' if ok else 'FAIL'} diloco-h8 comm reduction >= 4x "
            f"(measured {red:.1f}x: {sync['sync_rounds']} -> "
            f"{d8['sync_rounds']} sync rounds)"
        )
        ratio = d8["perplexity"] / sync["perplexity"]
        ok = ratio <= 1.02
        checks.append(
            f"{'PASS' if ok else 'FAIL'} diloco-h8 perplexity within 2% "
            f"of sync-dp ({d8['perplexity']} vs {sync['perplexity']}, "
            f"ratio {ratio:.4f})"
        )
    d32 = by.get("diloco-h32")
    if sync and d32:
        ratio = d32["perplexity"] / sync["perplexity"]
        checks.append(
            f"{'PASS' if ratio <= 1.02 else 'FAIL'} diloco-h32 "
            f"perplexity within 2% at "
            f"{sync['sync_rounds'] / max(d32['sync_rounds'], 1):.1f}x "
            f"fewer rounds ({d32['perplexity']} vs {sync['perplexity']})"
        )
    # Round 17: compressed-delta acceptance — bytes/token down ~4× vs
    # the round-14 DiLoCo row at ≤1% ppl cost. The counted dtype ratio
    # is 4× minus the per-tensor scale overhead (<0.5% at these shapes),
    # so the gate sits at 3.9×.
    q8 = by.get("diloco-h8-int8")
    if d8 and q8 and d8.get("bytes_per_token"):
        red = d8["bytes_per_token"] / max(q8["bytes_per_token"], 1e-9)
        ok = red >= 3.9
        checks.append(
            f"{'PASS' if ok else 'FAIL'} diloco-h8-int8 comm bytes/token "
            f">= 3.9x below diloco-h8 (measured {red:.2f}x: "
            f"{d8['bytes_per_token']} -> {q8['bytes_per_token']} "
            f"bytes/token; the 4x dtype ratio minus per-tensor scales)"
        )
        ratio = q8["perplexity"] / d8["perplexity"]
        ok = ratio <= 1.01
        checks.append(
            f"{'PASS' if ok else 'FAIL'} diloco-h8-int8 perplexity "
            f"within 1% of diloco-h8 ({q8['perplexity']} vs "
            f"{d8['perplexity']}, ratio {ratio:.4f}) — error feedback "
            "re-injects the rounding"
        )
    stream = by.get("diloco-h8-int8-stream")
    if d8 and stream:
        ratio = stream["perplexity"] / d8["perplexity"]
        ok = ratio <= 1.02
        extra = max(
            0.0,
            (stream["wall_s"] - by.get("diloco-h8-int8", d8)["wall_s"])
            / max(stream["wall_s"], 1e-9),
        )
        checks.append(
            f"{'PASS' if ok else 'FAIL'} diloco-h8-int8-stream "
            f"perplexity within 2% of diloco-h8 under the one-round-late "
            f"apply ({stream['perplexity']} vs {d8['perplexity']}, ratio "
            f"{ratio:.4f}); outer-round extra wall share vs the "
            f"non-overlapped row {extra:.2f} (CPU scan — the hidden "
            "all-reduce is the structural claim: the applied delta "
            "finished exchanging during the round that just ran)"
        )
    return checks


def markdown(results: list[dict], checks: list[str]) -> str:
    dev = results[0]["device"] if results else "?"
    lines = [
        "# DiLoCo vs sync-dp — perplexity vs sync rounds / wall-clock",
        "",
        "Generated by `python -m distributed_tensorflow_tpu.tools."
        "diloco_bench --write-docs` (train/local_sgd.py; ROADMAP item 5)."
        " Same model, corpus, inner optimizer (adam 3e-3) and global "
        "batch per row; only the gang sync cadence differs.",
        "",
        "| Row | Engine | H | outer lr | outer μ | Δ dtype | Held-out "
        "ppl | Sync rounds | Dense MB | Wire MB | B/token | "
        "Train dispatches | Wall s |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in results:
        dd = r.get("delta_dtype")
        lines.append(
            "| {row} | {engine} | {h} | {lr} | {mu} | {dd} | {ppl} | "
            "{rounds} | {mb} | {pmb} | {bpt} | {disp} | {wall} |".format(
                row=r["row"],
                engine=f"{r['engine']} ({r['device']})",
                h=r["sync_every"],
                lr="—" if r["outer_lr"] is None else r["outer_lr"],
                mu=(
                    "—"
                    if r["outer_momentum"] is None
                    else r["outer_momentum"]
                ),
                dd=(dd or "f32") + (" +ovl" if r.get("overlap") else ""),
                ppl=r["perplexity"],
                rounds=r["sync_rounds"],
                mb=r["allreduce_mb"],
                pmb=r.get("payload_mb", r["allreduce_mb"]),
                bpt=r.get("bytes_per_token", "—"),
                disp=r["train_dispatches"],
                wall=r["wall_s"],
            )
        )
    lines += [
        "",
        "Claim checks:",
        *(f"- {c}" for c in checks),
        "",
        f"Provenance: rows above were measured on `{dev}` — the "
        "perplexity / sync-round / bytes-per-token columns are the "
        "portable claim (counted, device-independent); the wall-clock "
        "column on a CPU container reflects vectorization, NOT "
        "communication. Wire MB is what actually crosses the gang "
        "(round 17: int8 error-feedback deltas with per-tensor scales — "
        "`+ovl` marks the overlapped exchange, whose outer update "
        "applies one round late so a real gang's all-reduce hides "
        "behind the next H inner steps; on CPU both rows pay the same "
        "in-graph cost, the hiding is the multi-host claim). The "
        "dispatch-amortization half (outer round = dispatch unit over "
        "the ~100 ms tunnel) and the TUNNEL-TPU wall-clock rows await "
        "the chip rerun (`--write-docs` there; verify-skill runbook). "
        "The async-beats-sync-under-failure scenario — a DiLoCo gang "
        "surviving a worker kill mid-run through the round-8 elastic "
        "resize — is proven end-to-end in "
        "tests/integration/test_fault_injection.py (RUN_SLOW), and the "
        "round-17 stale-tolerance half — a deliberately THROTTLED "
        "member contributing staleness-weighted deltas through the "
        "mailbox exchange while the gang runs on without it — in the "
        "same module's throttled-worker case.",
    ]
    return "\n".join(lines) + "\n"


def emit_bench_events(results: list[dict], events_path: str) -> int:
    """Gate-covered ``bench_point`` events: the comm-reduction factor and
    the sync/diloco perplexity ratio per diloco row — both fail LOW under
    the round-12 direction rule (unit is not ms/s), so a future change
    that erodes either parity claim fails the fast tier."""
    from distributed_tensorflow_tpu.observability.journal import (
        EventJournal,
    )

    by = {r["row"]: r for r in results}
    sync = by.get("sync-dp")
    if sync is None:
        return 0
    j = EventJournal(events_path, run_id="diloco_bench")
    n = 0
    try:
        for r in results:
            if not r["row"].startswith("diloco-h") or r["row"].endswith(
                "lrN"
            ):
                continue
            common = dict(
                tool="diloco_bench", device=r["device"], row=r["row"]
            )
            j.emit(
                "bench_point",
                name=f"{r['row']}/comm_reduction",
                value=round(
                    sync["sync_rounds"] / max(r["sync_rounds"], 1), 2
                ),
                unit="x",
                **common,
            )
            j.emit(
                "bench_point",
                name=f"{r['row']}/ppl_parity",
                value=round(
                    sync["perplexity"] / max(r["perplexity"], 1e-9), 4
                ),
                unit="ratio",
                **common,
            )
            n += 2
            # Round 17: wire bytes per trained token — a "bytes" unit,
            # so the gate fails HIGH (traffic creeping back up past the
            # compressed record is the regression).
            if r.get("bytes_per_token") is not None:
                j.emit(
                    "bench_point",
                    name=f"{r['row']}/comm_bytes_per_token",
                    value=float(r["bytes_per_token"]),
                    unit="bytes/token",
                    **common,
                )
                n += 1
    finally:
        j.close()
    return n


def _docs_root() -> str:
    return os.path.abspath(
        os.path.join(
            os.path.dirname(__file__), "..", "..", "docs", "benchmarks"
        )
    )


def render_from_payload(payload: dict) -> str:
    """md from the committed json — the staleness-guard entry point
    (tests/test_perf_record.py re-renders and compares byte-for-byte)."""
    return markdown(payload["rows"], payload["checks"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--json", type=str, default=None)
    p.add_argument(
        "--write-docs",
        action="store_true",
        help="rewrite docs/benchmarks/diloco.{md,json} and append the "
        "gate-covered bench_point events to docs/benchmarks/events.jsonl",
    )
    p.add_argument(
        "--events",
        default=None,
        help="append bench_point events to this events.jsonl (default "
        "with --write-docs: docs/benchmarks/events.jsonl)",
    )
    args = p.parse_args(argv)
    results = run_grid(
        epochs=args.epochs,
        workers=args.workers,
        print_fn=lambda *a: print(*a, file=sys.stderr),
    )
    checks = check_claims(results)
    payload = {"rows": results, "checks": checks}
    out = render_from_payload(payload)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1)
    events_path = args.events
    if args.write_docs:
        root = _docs_root()
        with open(os.path.join(root, "diloco.json"), "w") as f:
            json.dump(payload, f, indent=1)
        with open(os.path.join(root, "diloco.md"), "w") as f:
            f.write(out)
        events_path = events_path or os.path.join(root, "events.jsonl")
        print(f"wrote {root}/diloco.md and diloco.json", file=sys.stderr)
    if events_path:
        n = emit_bench_events(results, events_path)
        print(
            f"appended {n} bench_point events to {events_path}",
            file=sys.stderr,
        )
    return 0 if all(c.startswith("PASS") for c in checks) else 1


if __name__ == "__main__":
    raise SystemExit(main())
