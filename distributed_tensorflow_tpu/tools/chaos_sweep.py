"""Seed-swept chaos driver over the round-19 failpoint registry.

Every fault proof before this round was a bespoke integration script
(one SIGKILL, one throttle — tests/integration/test_fault_injection.py).
This tool is the robustness analogue of the regression gate: the fault
scenarios the docs claim to survive become a swept, repeatable matrix::

    python -m distributed_tensorflow_tpu.tools.chaos_sweep                # all
    python -m distributed_tensorflow_tpu.tools.chaos_sweep --seeds 0,1,2
    python -m distributed_tensorflow_tpu.tools.chaos_sweep \
        --schedules delta-torn,fleet-torn-result --json /tmp/chaos.json

Each SCHEDULE arms a deterministic failpoint spec (train/failpoints.py)
against one durability seam and asserts the invariants the docs already
claim — no data loss, recovery to the documented state, structured
``mailbox_corrupt``/``failpoint`` events, and the counters that make the
recovery observable. Each runs once per SEED; the seed deterministically
moves WHERE in the operation sequence the fault lands (``@N`` in the
spec), so a sweep covers a band of fault positions, not one anecdote.

Schedules (3 seams × 2 each):

- ``ckpt-torn-manifest`` — checkpoint corruption cascade: the newest one
  or two (seed parity) manifests torn at commit; restore must fall back
  to the newest VERIFYING step with the exact saved values.
- ``ckpt-kill-mid-save``  — a subprocess trainer SIGKILLed between its
  manifest tmp write and the atomic replace (``atomic.write.commit:
  kill@N``); the orbax payload is complete, so restore recovers the
  full step (unverified-trusted, the pre-manifest contract) and the
  only litter is a ``.tmp`` orphan the mailbox/manifest sweeps GC.
- ``delta-torn``          — a gang member's committed delta post torn;
  the peer's stale-weighted round proceeds WITHOUT it (skipped, never
  consumed, watermark advanced, ``mailbox_corrupt`` journaled) and the
  weighted mean over the surviving rounds is exact.
- ``delta-transient``     — ``delta.load:raise`` (FailpointError is an
  OSError): the unreadable post is retried next boundary with the
  watermark UNMOVED — the round's movement is consumed exactly once,
  late, never lost.
- ``fleet-torn-result``   — a replica's committed result file torn
  mid-failover; the router's poll quarantines it (never delivered,
  never re-read), the replica re-serves (the router re-admits anything
  without a result), and every trace id is delivered exactly once.
- ``fleet-garbage-json``  — raw garbage dropped into an outbox (storage
  corruption): quarantined once, valid results unaffected, second poll
  clean (the pre-round-19 infinite re-read is fixed).

Exit code 0 iff every (schedule, seed) cell passes; the one-line JSON
summary (bench.py idiom) carries the per-cell detail. The RUN_SLOW tier
runs one representative schedule per seam
(tests/integration/test_chaos_sweep.py).

Determinism: failpoints count hits, never clock or RNG; retry jitter in
any exercised path uses ``random.Random(seed)`` via the ``rng=`` knobs
(resilience.backoff_delay/retry/retry_io — the round-19 satellite), and
the sweep self-checks that the jittered delay sequence is reproducible.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import time

import numpy as np

from distributed_tensorflow_tpu.train import failpoints, resilience

_REPO = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")
)


class _Recorder:
    """Minimal journal: record events, write nothing (jax-free)."""

    path = None

    def __init__(self):
        self.events: list[dict] = []

    def emit(self, kind, **fields):
        ev = {"kind": kind, **fields}
        self.events.append(ev)
        return ev

    def kinds(self):
        return [e["kind"] for e in self.events]

    def flush(self):
        pass

    def close(self):
        pass


SCENARIOS: dict = {}


def scenario(name):
    def deco(fn):
        SCENARIOS[name] = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# Checkpoint seam.
# ---------------------------------------------------------------------------


def _mk_state(v):
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.parallel.strategy import TrainState

    return TrainState(
        {"w": jnp.full((4, 3), float(v)), "b": jnp.zeros((3,))},
        {"mu": jnp.ones((4, 3))},
        jnp.asarray(int(v), jnp.int32),
    )


@scenario("ckpt-torn-manifest")
def _ckpt_torn_manifest(seed, workdir):
    """Corruption cascade: tear the newest 1 (even seed) or 2 (odd seed)
    manifests; restore falls back to the newest verifying step with the
    exact saved values — a corrupt latest costs progress back to the
    last good save, never the run and never silent wrong data."""
    import warnings

    from distributed_tensorflow_tpu.train.supervisor import (
        Supervisor,
        latest_checkpoint_step,
    )

    d = os.path.join(workdir, "ck")
    sup = Supervisor(is_chief=True, checkpoint_dir=d)
    saves = 4
    torn = 1 + (seed % 2)  # newest 1 or 2 manifests torn
    spec = ",".join(
        f"ckpt.manifest:torn@{saves - i}" for i in range(torn)
    )
    failpoints.configure(spec)
    try:
        for s in range(1, saves + 1):
            sup.save(_mk_state(s), s)
    finally:
        failpoints.configure(None)
    expect = saves - torn
    assert latest_checkpoint_step(d, verify=True) == expect
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        restored, step = Supervisor(
            is_chief=True, checkpoint_dir=d
        ).prepare_or_restore(_mk_state(0))
    assert step == expect, f"restored step_{step}, wanted step_{expect}"
    got = float(np.asarray(restored.params["w"])[0, 0])
    assert got == float(expect), f"state value {got} != {expect}"
    return {"torn_manifests": torn, "restored_step": step}


_KILL_WORKER = r"""
import os, sys
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from distributed_tensorflow_tpu.parallel.strategy import TrainState
from distributed_tensorflow_tpu.train.supervisor import Supervisor

d = sys.argv[1]
sup = Supervisor(is_chief=True, checkpoint_dir=d)
for s in range(1, 6):
    sup.save(
        TrainState(
            {"w": jnp.full((4, 3), float(s)), "b": jnp.zeros((3,))},
            {"mu": jnp.ones((4, 3))},
            jnp.asarray(int(s), jnp.int32),
        ),
        s,
    )
print("UNREACHED" if os.environ.get("DTF_FAILPOINTS") else "DONE")
"""


@scenario("ckpt-kill-mid-save")
def _ckpt_kill_mid_save(seed, workdir):
    """Writer crash mid-commit: the subprocess saver is SIGKILLed between
    save N's manifest tmp write and the atomic replace. The orbax
    payload for step N is already complete, so restore recovers the FULL
    step (no manifest → unverified-trusted, the pre-round-6 contract);
    the only litter is a ``.tmp`` orphan, which the age-guarded sweep
    removes."""
    import warnings

    from distributed_tensorflow_tpu.train.supervisor import Supervisor

    d = os.path.join(workdir, "ck")
    os.makedirs(d)
    kill_at = 3 + (seed % 2)  # one atomic.write per save (the manifest)
    env = dict(os.environ)
    env["DTF_FAILPOINTS"] = f"atomic.write.commit:kill@{kill_at}"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_WORKER, d],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == -9, (
        f"rc={proc.returncode}, wanted SIGKILL (-9)\n{proc.stderr[-2000:]}"
    )
    assert "UNREACHED" not in proc.stdout
    # The kill landed mid-manifest-commit: step kill_at's payload is on
    # disk, its manifest is not, and the tmp orphan survives the crash.
    assert not os.path.exists(resilience.manifest_path(d, kill_at))
    orphans = [n for n in os.listdir(d) if ".tmp" in n]
    assert orphans, "writer crash should leave a .tmp orphan"
    swept = resilience.sweep_tmp_orphans(d, age_s=0.0)
    assert len(swept) == len(orphans)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        restored, step = Supervisor(
            is_chief=True, checkpoint_dir=d
        ).prepare_or_restore(_mk_state(0))
    assert step == kill_at, f"restored step_{step}, wanted step_{kill_at}"
    got = float(np.asarray(restored.params["w"])[0, 0])
    assert got == float(kill_at)
    return {
        "killed_at_save": kill_at,
        "restored_step": step,
        "orphans_swept": len(swept),
    }


# ---------------------------------------------------------------------------
# Delta-exchange seam (numpy-only: delta_dtype=None never touches jax).
# ---------------------------------------------------------------------------


def _leaf(v):
    return np.full((5, 7), float(v), np.float32)


@scenario("delta-torn")
def _delta_torn(seed, workdir):
    """Mid-gang committed-post corruption: one of rank 0's posts is torn
    at commit; rank 1's stale-weighted round proceeds without it —
    skipped, never consumed, watermark advanced (later rounds still
    arrive), one ``mailbox_corrupt`` event, and the weighted mean over
    the survivors is exact."""
    from distributed_tensorflow_tpu.train.local_sgd import (
        DeltaExchange,
        staleness_weight,
    )

    d = os.path.join(workdir, "mail")
    rounds = 5
    torn_hit = 2 + (seed % 3)  # post() hit N ↔ round N-1
    rec = _Recorder()
    writer = DeltaExchange(d, 0, 2, stale_limit=rounds + 2)
    reader = DeltaExchange(d, 1, 2, stale_limit=rounds + 2, journal=rec)
    failpoints.configure(f"delta.post:torn@{torn_hit}")
    try:
        for r in range(rounds):
            writer.post(r, [_leaf(r + 1)])
    finally:
        failpoints.configure(None)
    own = [_leaf(100.0)]
    mean, total, contributors = reader.weighted_delta(rounds - 1, own)
    torn_round = torn_hit - 1
    survive = [r for r in range(rounds) if r != torn_round]
    assert reader.corrupt_posts == 1
    assert rec.kinds() == ["mailbox_corrupt"]
    assert rec.events[0]["round"] == torn_round
    assert [c[0] for c in contributors] == [1] + [0] * len(survive)
    # Exact weighted mean over the surviving rounds (own weight 1).
    w = [
        staleness_weight(rounds - 1 - r, reader.stale_limit)
        for r in survive
    ]
    want_total = 1.0 + sum(w)
    want = (100.0 + sum(wi * (r + 1) for wi, r in zip(w, survive))) / (
        want_total
    )
    assert abs(total - want_total) < 1e-6
    assert abs(float(mean[0][0, 0]) - want) < 1e-5, (
        f"mean {float(mean[0][0, 0])} != {want}"
    )
    return {"torn_round": torn_round, "survivors": len(survive)}


@scenario("delta-transient")
def _delta_transient(seed, workdir):
    """Transient unreadability: ``delta.load:raise`` makes the first
    peer read fail like a shared-fs hiccup (FailpointError IS an
    OSError). The watermark must NOT advance — the next boundary
    consumes the same round exactly once, one round later. Nothing
    lost, nothing double-applied."""
    from distributed_tensorflow_tpu.train.local_sgd import DeltaExchange

    d = os.path.join(workdir, "mail")
    writer = DeltaExchange(d, 0, 2, stale_limit=4)
    reader = DeltaExchange(d, 1, 2, stale_limit=4)
    val = float(1 + seed)
    writer.post(0, [_leaf(val)])
    failpoints.configure("delta.load:raise@1")
    try:
        got = reader.gather(0)
    finally:
        failpoints.configure(None)
    assert got == [] and reader._consumed == {}, (
        "transient failure must not consume or advance the watermark"
    )
    got = reader.gather(1)  # next boundary: same post, age 1, consumed
    assert len(got) == 1 and got[0][0] == 0 and got[0][1] == 1
    assert float(got[0][3][0][0, 0]) == val
    assert reader.gather(2) == [], "a post is consumed exactly once"
    assert reader.corrupt_posts == 0  # transient ≠ corrupt
    return {"retried_age": 1}


# ---------------------------------------------------------------------------
# Fleet-mailbox seam (jax-free).
# ---------------------------------------------------------------------------


@scenario("fleet-torn-result")
def _fleet_torn_result(seed, workdir):
    """Torn result mid-failover: of R committed results one is torn; the
    router's poll delivers the others and quarantines the torn file
    (never delivered, never re-read). The replica re-serves the one
    request the router still sees as in-flight — the round-16 zero-loss
    protocol: anything without a result re-admits — and every trace is
    delivered exactly once."""
    from distributed_tensorflow_tpu.serve_fleet import MailboxClient

    rec = _Recorder()
    box = MailboxClient(os.path.join(workdir, "r0"), journal=rec)
    n = 4
    torn_hit = 1 + (seed % n)
    traces = [f"t{i}" for i in range(n)]
    payloads = {t: {"trace": t, "out": [i, i + 1]} for i, t in enumerate(traces)}
    failpoints.configure(f"fleet.result:torn@{torn_hit}")
    try:
        for t in traces:
            box.put_result(payloads[t])
    finally:
        failpoints.configure(None)
    first = box.poll_results()
    got = {p["trace"] for p in first}
    torn_trace = traces[torn_hit - 1]
    assert got == set(traces) - {torn_trace}
    assert box.corrupt_files == 1
    assert rec.kinds() == ["mailbox_corrupt"]
    assert rec.events[0]["action"] == "quarantined"
    assert box.poll_results() == [], "quarantined file must not re-read"
    # Failover re-serve: the router re-admits the traceless request and
    # the (re)serving replica commits the identical deterministic result.
    box.put_result(payloads[torn_trace])
    second = box.poll_results()
    assert [p["trace"] for p in second] == [torn_trace]
    assert second[0] == payloads[torn_trace], "re-served result intact"
    delivered = [p["trace"] for p in first + second]
    assert sorted(delivered) == sorted(traces), "each trace exactly once"
    return {"torn_trace": torn_trace, "delivered": len(delivered)}


@scenario("fleet-garbage-json")
def _fleet_garbage_json(seed, workdir):
    """Storage corruption: raw garbage bytes appear as a committed
    ``.json`` in the outbox. The poll quarantines it once (counted,
    journaled), delivers the valid results untouched, and the next poll
    is clean — the pre-round-19 behavior re-read the garbage forever."""
    from distributed_tensorflow_tpu.serve_fleet import MailboxClient

    rec = _Recorder()
    box = MailboxClient(os.path.join(workdir, "r0"), journal=rec)
    box.put_result({"trace": "ok1", "out": [1]})
    rng = random.Random(seed)
    junk = bytes(rng.randrange(256) for _ in range(64))
    with open(os.path.join(box.outbox, "00000000-junk.json"), "wb") as f:
        f.write(junk)
    box.put_result({"trace": "ok2", "out": [2]})
    got = {p["trace"] for p in box.poll_results()}
    assert got == {"ok1", "ok2"}
    assert box.corrupt_files == 1
    assert box.poll_results() == [] and os.listdir(box.outbox) == []
    assert rec.events[0]["reason"] in ("json", "crc")
    return {"junk_bytes": len(junk)}


# ---------------------------------------------------------------------------
# Driver.
# ---------------------------------------------------------------------------


def _jitter_determinism(seed: int) -> bool:
    """Satellite pin, swept per seed: the jittered backoff sequence is a
    pure function of the seeded rng."""
    seq = [
        resilience.backoff_delay(
            a, backoff=0.25, jitter=0.5, rng=random.Random(seed)
        )
        for a in range(4)
    ]
    again = [
        resilience.backoff_delay(
            a, backoff=0.25, jitter=0.5, rng=random.Random(seed)
        )
        for a in range(4)
    ]
    return seq == again


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", default="0,1", help="comma-separated ints")
    ap.add_argument(
        "--schedules",
        default="all",
        help=f"comma-separated from: {','.join(SCENARIOS)} (or 'all')",
    )
    ap.add_argument("--json", dest="json_out", default=None)
    args = ap.parse_args(argv)

    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    names = (
        list(SCENARIOS)
        if args.schedules == "all"
        else [s.strip() for s in args.schedules.split(",") if s.strip()]
    )
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown schedule(s): {unknown}; have {list(SCENARIOS)}")

    cells = []
    failed = 0
    for name in names:
        for seed in seeds:
            failpoints.configure(None)
            t0 = time.perf_counter()
            cell = {"schedule": name, "seed": seed}
            with tempfile.TemporaryDirectory() as workdir:
                try:
                    detail = SCENARIOS[name](seed, workdir) or {}
                    cell.update(ok=True, **detail)
                except Exception as exc:  # noqa: BLE001 — cell verdicts
                    failed += 1
                    cell.update(
                        ok=False,
                        error=f"{type(exc).__name__}: {exc}",
                    )
            failpoints.configure(None)
            cell["wall_s"] = round(time.perf_counter() - t0, 3)
            cells.append(cell)
            status = "ok" if cell["ok"] else "FAIL"
            print(
                f"chaos {name} seed={seed}: {status} "
                f"({cell['wall_s']}s)",
                file=sys.stderr,
            )

    summary = {
        "tool": "chaos_sweep",
        "schedules": names,
        "seeds": seeds,
        "cells": cells,
        "failed": failed,
        "jitter_deterministic": all(_jitter_determinism(s) for s in seeds),
        "ok": failed == 0,
    }
    line = json.dumps(summary)
    print(line)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0 if summary["ok"] and summary["jitter_deterministic"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
