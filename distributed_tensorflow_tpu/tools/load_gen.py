"""Scenario-diverse deterministic load generator for the serving stack.

Round 21 (overload robustness): the deadline/priority scheduler in
serve.py and the per-class weighted-fair router in serve_fleet.py make
claims that only show under SHAPED load — a steady trickle never trips
saturation shedding, a uniform workload never exercises weighted
fairness, and an all-greedy mix never touches the sampled key chain
under displacement. This module generates that load: six named
scenarios, each a pure function of ``(seed, n, vocab, rate)`` (stdlib
``random.Random`` only — bit-reproducible across hosts, no numpy global
state), drivable against a live :class:`~..serve.TextServer`, a
:class:`~..serve_fleet.ReplicaRouter`, or the FakeClock test harness,
and summarized per priority class from round-12 journal events alone.

Scenarios::

    steady        Poisson arrivals at ``rate`` rps, mid prompts/decodes
    bursty        ON/OFF square wave: 4x rate bursts, silent gaps
    long_prefill  prompt-heavy (near-bucket prompts, short decodes)
    chat          decode-heavy (short prompts, long generations)
    mixed_sampling half greedy / half nucleus-sampled (per-request seed)
    priority_mix  3 classes: interactive p2 + tight deadline, standard
                  p1 + loose deadline, batch p0 + no deadline

The summary's TTFT is **submit -> first service** (TextServer
``admission`` / router ``request_route``) — the scheduler observable
both targets share and the one the round-21 scheduler reorders; latency
is submit -> terminal. Shed rate is per class, the round-21 loudness
contract made measurable (``shed_rate_{class}`` fails HIGH under the
regression gate).

jax-free at import (the serve_fleet convention): scenario generation and
journal summarization run anywhere; only :func:`drive` against a real
TextServer touches jax, inside the call.

Usage::

    python -m distributed_tensorflow_tpu.tools.load_gen --scenario bursty
    python -m distributed_tensorflow_tpu.tools.load_gen --list
"""

from __future__ import annotations

import argparse
import json
import random
import time


class LoadRequest:
    """One generated request: arrival offset + everything submit needs."""

    __slots__ = (
        "at_s", "tokens", "max_new", "priority", "deadline_s", "greedy",
        "temperature", "top_p", "seed",
    )

    def __init__(
        self,
        at_s: float,
        tokens: list[int],
        max_new: int,
        *,
        priority: int = 0,
        deadline_s: float | None = None,
        greedy: bool = True,
        temperature: float = 1.0,
        top_p: float = 1.0,
        seed: int = 0,
    ):
        self.at_s = float(at_s)
        self.tokens = list(tokens)
        self.max_new = int(max_new)
        self.priority = int(priority)
        self.deadline_s = deadline_s
        self.greedy = bool(greedy)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.seed = int(seed)

    def to_dict(self) -> dict:
        d = {
            "at_s": round(self.at_s, 6),
            "prompt_len": len(self.tokens),
            "max_new": self.max_new,
            "priority": self.priority,
            "greedy": self.greedy,
        }
        if self.deadline_s is not None:
            d["deadline_s"] = self.deadline_s
        return d


def _prompt(rng: random.Random, vocab: int, lo: int, hi: int) -> list[int]:
    n = rng.randint(lo, hi)
    return [rng.randrange(vocab) for _ in range(n)]


def _poisson_arrivals(rng: random.Random, n: int, rate: float):
    """Cumulative exponential gaps — the memoryless arrival process."""
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(t)
    return out


def _steady(rng, n, vocab, rate):
    return [
        LoadRequest(t, _prompt(rng, vocab, 8, 48), rng.randint(16, 48))
        for t in _poisson_arrivals(rng, n, rate)
    ]


def _bursty(rng, n, vocab, rate):
    """ON/OFF square wave: bursts at 4x the nominal rate separated by
    silent gaps of equal expected mass — the overload-then-idle shape
    that exercises saturation shedding and queue drain."""
    out, t = [], 0.0
    while len(out) < n:
        burst = min(rng.randint(4, 8), n - len(out))
        for _ in range(burst):
            t += rng.expovariate(4.0 * rate)
            out.append(
                LoadRequest(
                    t, _prompt(rng, vocab, 8, 48), rng.randint(16, 48)
                )
            )
        t += burst / rate  # the OFF gap carries the deferred mass
    return out


def _long_prefill(rng, n, vocab, rate):
    return [
        LoadRequest(t, _prompt(rng, vocab, 40, 60), rng.randint(4, 12))
        for t in _poisson_arrivals(rng, n, rate)
    ]


def _chat(rng, n, vocab, rate):
    return [
        LoadRequest(t, _prompt(rng, vocab, 4, 16), rng.randint(48, 96))
        for t in _poisson_arrivals(rng, n, rate)
    ]


def _mixed_sampling(rng, n, vocab, rate):
    out = []
    for i, t in enumerate(_poisson_arrivals(rng, n, rate)):
        sampled = rng.random() < 0.5
        out.append(
            LoadRequest(
                t,
                _prompt(rng, vocab, 8, 32),
                rng.randint(16, 48),
                greedy=not sampled,
                temperature=0.8 if sampled else 1.0,
                top_p=0.95 if sampled else 1.0,
                seed=rng.randrange(1 << 30) if sampled else 0,
            )
        )
    return out


def _priority_mix(rng, n, vocab, rate):
    """Three service classes: interactive (p2, tight deadline), standard
    (p1, loose deadline), batch (p0, none). Under ≥2x-capacity overload
    the round-21 contract is: every shed lands on the batch class, every
    deadline-capable interactive request completes."""
    out = []
    for t in _poisson_arrivals(rng, n, rate):
        u = rng.random()
        if u < 0.3:
            out.append(
                LoadRequest(
                    t, _prompt(rng, vocab, 4, 16), rng.randint(8, 16),
                    priority=2, deadline_s=30.0,
                )
            )
        elif u < 0.6:
            out.append(
                LoadRequest(
                    t, _prompt(rng, vocab, 8, 32), rng.randint(16, 32),
                    priority=1, deadline_s=120.0,
                )
            )
        else:
            out.append(
                LoadRequest(
                    t, _prompt(rng, vocab, 8, 48), rng.randint(24, 48),
                )
            )
    return out


SCENARIOS = {
    "steady": _steady,
    "bursty": _bursty,
    "long_prefill": _long_prefill,
    "chat": _chat,
    "mixed_sampling": _mixed_sampling,
    "priority_mix": _priority_mix,
}


def generate(
    scenario: str,
    *,
    seed: int = 0,
    n: int = 32,
    vocab: int = 512,
    rate: float = 50.0,
) -> list[LoadRequest]:
    """The scenario's request list — deterministic in every argument.
    ``rate`` is nominal requests/second of SIMULATED arrival time; the
    driver compresses or stretches it against the target's real clock."""
    try:
        fn = SCENARIOS[scenario]
    except KeyError:
        raise ValueError(
            f"unknown scenario {scenario!r}; one of {sorted(SCENARIOS)}"
        ) from None
    # A str seed routes through random's deterministic sha512 path; a
    # tuple would go through hash(), which PYTHONHASHSEED randomizes.
    rng = random.Random(f"{seed}:{scenario}")
    reqs = fn(rng, n, vocab, rate)
    assert len(reqs) == n
    return reqs


# -- driving a live target -------------------------------------------------


def _submit(target, req: LoadRequest):
    """Adapter over the two servable targets. The router takes a plain
    config dict (it travels the mailbox); TextServer takes the real
    GenerationConfig. Both share the round-21 submit keywords."""
    if hasattr(target, "replicas"):  # ReplicaRouter
        cfg = {"max_new": req.max_new, "greedy": req.greedy}
        if not req.greedy:
            cfg.update(
                temperature=req.temperature, top_p=req.top_p, seed=req.seed
            )
        return target.submit(
            req.tokens, cfg, deadline_s=req.deadline_s,
            priority=req.priority,
        )
    from distributed_tensorflow_tpu.serve import GenerationConfig

    cfg = GenerationConfig(
        max_new=req.max_new, greedy=req.greedy,
        temperature=req.temperature, top_p=req.top_p, seed=req.seed,
    )
    return target.submit(
        req.tokens, cfg, deadline_s=req.deadline_s, priority=req.priority
    )


def drive(
    target,
    requests: list[LoadRequest],
    *,
    clock=None,
    sleep=None,
    timeout_s: float = 300.0,
) -> dict:
    """Replay the scenario against a live TextServer or ReplicaRouter:
    submit each request when its arrival offset elapses (by ``clock`` —
    inject the FakeClock pair for simulated-time tests), stepping the
    target in between, until every submitted request is terminal.
    Returns ``{"rids": [...], "wall_s": ...}``; per-request outcomes are
    read from the journal (:func:`summarize`), not collected here — the
    journal is the operator's own path and the one the summary claims
    hold on."""
    clock = clock or time.perf_counter
    sleep = sleep or time.sleep
    pending = sorted(requests, key=lambda r: r.at_s)
    rids: list = []
    rejected = 0
    t0 = clock()
    deadline = t0 + timeout_s
    i = 0
    while True:
        now = clock() - t0
        while i < len(pending) and pending[i].at_s <= now:
            try:
                rids.append(_submit(target, pending[i]))
            except Exception as exc:
                # QueueFull is the server's loud backpressure — a load
                # generator absorbs it (a real client would retry);
                # matched by name so the module stays jax-free.
                if type(exc).__name__ != "QueueFull":
                    raise
                rejected += 1
            i += 1
        busy = target.step()
        done = i >= len(pending) and all(target.done(r) for r in rids)
        if done:
            break
        if clock() > deadline:
            break
        if not busy:
            if i < len(pending):
                sleep(max(min(pending[i].at_s - (clock() - t0), 0.05), 0.0))
            else:
                sleep(0.001)
    return {"rids": rids, "rejected": rejected, "wall_s": clock() - t0}


# -- per-class summary from journal events ---------------------------------

_FIRST_SERVICE = ("admission", "request_route")


def summarize(events: list[dict]) -> dict:
    """Per-priority-class outcome metrics from round-12 journal events —
    works on a TextServer journal (``admission``/``completion``/
    ``request_shed``) and a router journal (``request_route``/
    ``fleet_result``/``request_shed``) alike. Returns::

        {"classes": {prio: {requests, done, shed, cancelled, failed,
                            migrated, shed_rate, ttft_s: {p50, p95},
                            latency_s: {p50, p95}}},
         "requests": N, "shed_rate": overall}

    Router journals from a disaggregated fleet (round 23) additionally
    yield top-level ``migrated`` and ``kv_migration_bytes_per_req``
    (mean bytes over the ``request_migrated`` events).
    """
    sub: dict = {}
    first: dict = {}
    term: dict = {}
    migr: dict = {}
    for ev in events:
        kind, rid = ev.get("kind"), ev.get("rid")
        if rid is None:
            continue
        if kind == "request_submit":
            sub[rid] = (ev.get("ts"), int(ev.get("priority", 0)))
        elif kind == "request_migrated":
            # Round 23 (disaggregated fleet): the prefill→decode handoff.
            migr[rid] = ev.get("nbytes") or 0
        elif kind in _FIRST_SERVICE:
            first.setdefault(rid, ev.get("ts"))
        elif kind == "completion":
            term[rid] = ("done", ev.get("ts"))
        elif kind == "fleet_result":
            status = ev.get("status", "done")
            term[rid] = (
                "done" if status == "done" else status, ev.get("ts")
            )
        elif kind == "request_shed":
            term[rid] = ("shed", ev.get("ts"))
        elif kind == "request_cancelled":
            term[rid] = ("cancelled", ev.get("ts"))
    classes: dict = {}
    for rid, (ts0, prio) in sub.items():
        c = classes.setdefault(
            prio,
            {
                "requests": 0, "done": 0, "shed": 0, "cancelled": 0,
                "failed": 0, "migrated": 0, "_ttft": [], "_lat": [],
            },
        )
        c["requests"] += 1
        if rid in migr:
            c["migrated"] += 1
        status, ts1 = term.get(rid, (None, None))
        if status == "done":
            c["done"] += 1
            if ts1 is not None and ts0 is not None:
                c["_lat"].append(ts1 - ts0)
            if rid in first and first[rid] is not None and ts0 is not None:
                c["_ttft"].append(first[rid] - ts0)
        elif status == "shed":
            c["shed"] += 1
        elif status == "cancelled":
            c["cancelled"] += 1
        elif status in ("rejected", "failed"):
            c["failed"] += 1

    def pct(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(int(q * len(vals)), len(vals) - 1)], 6)

    out: dict = {}
    for prio, c in sorted(classes.items()):
        out[prio] = {
            "requests": c["requests"],
            "done": c["done"],
            "shed": c["shed"],
            "cancelled": c["cancelled"],
            "failed": c["failed"],
            "migrated": c["migrated"],
            "shed_rate": round(c["shed"] / max(c["requests"], 1), 4),
            "ttft_s": {"p50": pct(c["_ttft"], 0.5),
                       "p95": pct(c["_ttft"], 0.95)},
            "latency_s": {"p50": pct(c["_lat"], 0.5),
                          "p95": pct(c["_lat"], 0.95)},
        }
    total = sum(c["requests"] for c in out.values())
    shed = sum(c["shed"] for c in out.values())
    summary = {
        "classes": out,
        "requests": total,
        "shed_rate": round(shed / max(total, 1), 4),
    }
    if migr:
        summary["migrated"] = len(migr)
        summary["kv_migration_bytes_per_req"] = round(
            sum(migr.values()) / len(migr), 1
        )
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="steady",
                    choices=sorted(SCENARIOS))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--rate", type=float, default=50.0)
    ap.add_argument("--list", action="store_true",
                    help="list scenario names and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name in sorted(SCENARIOS):
            print(name)
        return 0
    reqs = generate(
        args.scenario, seed=args.seed, n=args.n, vocab=args.vocab,
        rate=args.rate,
    )
    for r in reqs:
        print(json.dumps(r.to_dict()))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
