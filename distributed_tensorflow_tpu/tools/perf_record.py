"""Number-of-record resolver: the newest driver ``BENCH_r*.json`` wins.

VERDICT r5 weak #6: the band rule says latest-wins, but the prose in
``docs/performance.md`` / ``docs/benchmarks/README.md`` / ``README.md``
hard-coded one artifact by name and went stale the moment the next
driver run landed. This tool makes the citation GENERATED: the three
docs carry a one-line record citation between
``<!-- bench-record -->…<!-- /bench-record -->`` markers, and

    python -m distributed_tensorflow_tpu.tools.perf_record --write-docs

rewrites every marker span from the newest ``BENCH_r*.json`` at the repo
root (no chip needed — pure file rewriting, same offline contract as
``lm_bench --recompute-docs``). ``tests/test_tools_and_failure.py`` pins
the committed docs against the newest committed artifact, so landing a
new driver artifact without regenerating fails the fast tier instead of
shipping a stale number-of-record.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_BENCH = re.compile(r"^BENCH_r(\d+)\.json$")
_SPAN = re.compile(
    r"<!-- bench-record -->.*?<!-- /bench-record -->", re.DOTALL
)

# Files carrying a bench-record marker span, relative to the repo root.
DOC_FILES = ("docs/performance.md", "docs/benchmarks/README.md", "README.md")


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def latest_bench(root: str | None = None) -> tuple[str, dict] | None:
    """(filename, parsed payload) of the highest-numbered BENCH_r*.json
    whose payload parsed (rc 0 and a metric line), or None."""
    root = root or repo_root()
    best = None
    for name in os.listdir(root):
        m = _BENCH.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(root, name)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = data.get("parsed")
        # Everything citation() renders must be present — a partially
        # parsed artifact is skipped, not crashed on.
        if not parsed or any(
            k not in parsed for k in ("value", "vs_baseline", "impl")
        ):
            continue
        n = int(m.group(1))
        if best is None or n > best[0]:
            best = (n, name, parsed)
    if best is None:
        return None
    return best[1], best[2]


def citation(name: str, parsed: dict) -> str:
    """The generated record line (identical in every doc)."""
    return (
        f"<!-- bench-record -->number-of-record: latest driver artifact "
        f"`{name}` — {parsed['value']:,.0f} examples/sec/chip "
        f"({parsed['vs_baseline']:,.1f}x the reference's 42k), "
        f"impl `{parsed['impl']}`; regenerate this line with "
        f"`python -m distributed_tensorflow_tpu.tools.perf_record "
        f"--write-docs`<!-- /bench-record -->"
    )


def write_docs(root: str | None = None, print_fn=print) -> bool:
    """Rewrite every marker span from the newest artifact. Returns True
    when anything changed."""
    root = root or repo_root()
    latest = latest_bench(root)
    if latest is None:
        raise SystemExit("no parseable BENCH_r*.json at the repo root")
    line = citation(*latest)
    changed = False
    for rel in DOC_FILES:
        path = os.path.join(root, rel)
        with open(path) as f:
            text = f.read()
        new, n = _SPAN.subn(line, text)
        if n == 0:
            raise SystemExit(f"{rel}: no <!-- bench-record --> marker span")
        if new != text:
            with open(path, "w") as f:
                f.write(new)
            changed = True
            print_fn(f"{rel}: updated to {latest[0]}")
        else:
            print_fn(f"{rel}: already current ({latest[0]})")
    return changed


def check_docs(root: str | None = None) -> list[str]:
    """Names of doc files whose record span is stale (test hook)."""
    root = root or repo_root()
    latest = latest_bench(root)
    if latest is None:
        return []
    line = citation(*latest)
    stale = []
    for rel in DOC_FILES:
        with open(os.path.join(root, rel)) as f:
            text = f.read()
        spans = _SPAN.findall(text)
        if not spans or any(s != line for s in spans):
            stale.append(rel)
    return stale


def journal_points(path: str) -> list[dict]:
    """``bench_point`` events from an event journal (round 10: serve_bench
    and lm_bench emit their measured points as journal events — the BENCH
    artifacts, docs tables, and journal share one source). Latest wins
    per (tool, name), mirroring the BENCH_r* latest-wins band rule."""
    from distributed_tensorflow_tpu.observability.journal import read_events

    latest: dict = {}
    for ev in read_events(path, kind="bench_point"):
        latest[(ev.get("tool"), ev.get("name"))] = ev
    return [latest[k] for k in sorted(latest, key=str)]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--write-docs", action="store_true")
    parser.add_argument("--check", action="store_true")
    parser.add_argument(
        "--journal",
        metavar="EVENTS",
        help="summarize bench_point events from an events.jsonl "
        "(latest per tool/name) instead of the BENCH_r* artifacts",
    )
    args = parser.parse_args(argv)
    if args.journal:
        points = journal_points(args.journal)
        print(json.dumps(points))
        return 0 if points else 1
    if args.write_docs:
        write_docs()
        return 0
    if args.check:
        stale = check_docs()
        if stale:
            print(f"stale bench-record citations: {', '.join(stale)}")
            return 1
        print("bench-record citations current")
        return 0
    latest = latest_bench()
    print(json.dumps(None if latest is None else {"file": latest[0], **latest[1]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
