"""Local cluster launcher — the reference's nohup-per-task workflow, automated.

The reference ran every topology by hand-launching one process per task::

    nohup python tfdist_between.py --job_name=ps --task_index=0 > ps.log 2>&1 &
    nohup python tfdist_between.py --job_name=worker --task_index=0 > w0.log ...

(reference README.md:34-35, 58-60; C17 in SURVEY.md §2). This tool does the
same thing in one command, against any script that accepts the standard
``--job_name/--task_index`` flags::

    python -m distributed_tensorflow_tpu.tools.launch_local \
        --workers 2 --ps 1 --logdir ./task_logs -- python examples/between_sync.py

One OS process per task, stdout/stderr redirected to ``<logdir>/<role><i>.log``
exactly like the nohup recipe, non-zero exit if any worker fails. ps tasks
are launched too (they no-op and exit, preserving launcher compatibility).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def launch(
    command: list[str],
    num_workers: int,
    num_ps: int = 0,
    logdir: str = "./task_logs",
    env: dict | None = None,
    wait: bool = True,
) -> int:
    os.makedirs(logdir, exist_ok=True)
    procs: list[tuple[str, subprocess.Popen]] = []
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    for role, count in (("ps", num_ps), ("worker", num_workers)):
        for i in range(count):
            log_path = os.path.join(logdir, f"{role}{i}.log")
            f = open(log_path, "w")
            p = subprocess.Popen(
                command + [f"--job_name={role}", f"--task_index={i}"],
                stdout=f,
                stderr=subprocess.STDOUT,
                env=base_env,
            )
            procs.append((f"{role}{i}", p))
    if not wait:
        return 0
    rc = 0
    for name, p in procs:
        code = p.wait()
        print(f"{name}: exit {code}")
        if code != 0 and name.startswith("worker"):
            rc = 1
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workers", type=int, required=True)
    parser.add_argument("--ps", type=int, default=0)
    parser.add_argument("--logdir", type=str, default="./task_logs")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- command to launch per task")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("missing command after --")
    return launch(command, args.workers, args.ps, args.logdir)


if __name__ == "__main__":
    sys.exit(main())
