"""Local cluster launcher — the reference's nohup-per-task workflow, automated
and (round 7) supervised.

The reference ran every topology by hand-launching one process per task::

    nohup python tfdist_between.py --job_name=ps --task_index=0 > ps.log 2>&1 &
    nohup python tfdist_between.py --job_name=worker --task_index=0 > w0.log ...

(reference README.md:34-35, 58-60; C17 in SURVEY.md §2). This tool does the
same thing in one command, against any script that accepts the standard
``--job_name/--task_index`` flags::

    python -m distributed_tensorflow_tpu.tools.launch_local \
        --workers 2 --ps 1 --logdir ./task_logs -- python examples/between_sync.py

One OS process per task, stdout/stderr redirected to ``<logdir>/<role><i>.log``
exactly like the nohup recipe, non-zero exit if any worker fails. ps tasks
are launched too (they no-op and exit, preserving launcher compatibility).

``--max-restarts N`` (round 7) upgrades the one-shot spawner into the
elastic agent's driver (train/elastic.py): each worker gets a supervising
:class:`ElasticAgent`; a member that exits non-zero — or, with
``--heartbeat-port``, goes heartbeat-dead or live-but-stalled past
``--stall-timeout-ms`` — triggers a GANG restart: every worker is killed
and relaunched after a jittered exponential backoff, at most N times, with
a structured ``Restart:`` line and a ``restart`` tfevents scalar per event.
Relaunched workers re-bootstrap ``jax.distributed`` (bounded retried
initialize, ``cluster.bounded_initialize``) and resume from the newest
valid checkpoint — arm ``DTF_CHECKPOINT`` so there is something to resume.
The driver hosts the heartbeat detector itself (out-of-band of the job)
and points the workers at it via ``DTF_HEARTBEAT_HOST``/``_PORT``;
``max_restarts=0`` (default) preserves the old fail-stop behavior exactly.

``--min-workers M`` (round 8) arms shrink-to-fit resize on top: a worker
whose slot is LOST — marker file ``<logdir>/worker<i>.lost`` present, the
driver's host-availability probe — and not replaced within
``--rejoin-timeout-s`` is benched, and the survivors relaunch alone at
the reduced world size (down to M; below: fail-stop). Resized
incarnations are spawned with compact ``--task_index`` ranks and
``DTF_WORLD_SIZE``/``DTF_WORKER_RANKS`` in the env, which
``launch.cluster_from_env`` resolves to the surviving sub-cluster
(``ClusterConfig.subset``) so the workers re-bootstrap
``jax.distributed`` at the new ``num_processes`` and cross-restore the
old-world checkpoint. Deleting the ``.lost`` marker registers a
replacement: the gang grows back at the next poll. An external scheduler
manages the markers in production; ``--drive-mode
kill-without-replace|kill-then-replace`` makes the driver itself stage
the scenario (SIGKILL the highest worker after ``--drive-after-s``, mark
it lost, and — in then-replace mode — clear the marker after
``--drive-replace-after-s``) for demos and the integration tests.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time


def _spawn_task(
    command: list[str],
    role: str,
    index: int,
    logdir: str,
    env: dict,
    mode: str = "wb",
    log_index: int | None = None,
):
    """One task process, stdout+stderr to ``<logdir>/<role><i>.log``. The
    first incarnation truncates (the pre-round-7 behavior, unchanged); a
    gang RELAUNCH passes ``mode="ab"`` so the restarted incarnation's log
    continues the same file instead of erasing the failure it is
    recovering from. ``log_index`` keeps the log under the member's
    ORIGINAL id when a resize remaps ``index`` to a compact rank (one
    member, one log file, across every topology it serves in)."""
    log_path = os.path.join(
        logdir, f"{role}{index if log_index is None else log_index}.log"
    )
    f = open(log_path, mode)
    try:
        return subprocess.Popen(
            command + [f"--job_name={role}", f"--task_index={index}"],
            stdout=f,
            stderr=subprocess.STDOUT,
            env=env,
        )
    finally:
        # Popen inherited the descriptor; closing ours leaks nothing and a
        # relaunch reopens fresh (no shared offsets across incarnations).
        f.close()


def lost_marker(logdir: str, worker: int) -> str:
    """Path of worker ``i``'s host-lost marker: present = no host backs
    the slot (the driver's availability probe); deleting it registers a
    replacement. The file-based contract keeps the probe scriptable by
    any external scheduler."""
    return os.path.join(logdir, f"worker{worker}.lost")


def heartbeat_file(logdir: str, worker: int) -> str:
    """Path of worker ``i``'s progress-heartbeat file (round 22): the
    trainer mtime-bumps it at every step/epoch boundary
    (Supervisor.report_progress via ``DTF_HEARTBEAT_FILE``); the driver's
    watchdog reads its age. File-based like the lost marker — any
    external scheduler can watch it."""
    return os.path.join(logdir, f"worker{worker}.heartbeat")


def _launch_elastic(
    command: list[str],
    num_workers: int,
    logdir: str,
    base_env: dict,
    *,
    max_restarts: int,
    heartbeat_port: int | None,
    heartbeat_timeout_ms: int,
    heartbeat_grace_ms: int | None,
    stall_timeout_ms: int,
    stall_after_s: float = 0.0,
    backoff: float = 1.0,
    poll_interval: float = 0.5,
    min_workers: int | None = None,
    rejoin_timeout_s: float = 30.0,
    independent: bool = False,
    drive_mode: str | None = None,
    drive_after_s: float = 8.0,
    drive_replace_after_s: float = 10.0,
    metrics_port: int | None = None,
    print_fn=print,
) -> int:
    from distributed_tensorflow_tpu.train.elastic import (
        ElasticAgent,
        ElasticGang,
        HeartbeatHealth,
    )

    env = dict(base_env)
    health_factory = None
    summary_writer = None
    if heartbeat_port:
        # The driver hosts the detector (out-of-band of the job); workers
        # learn where to beat from the env, chief included
        # (cluster.bootstrap heartbeat_host mode).
        env["DTF_HEARTBEAT_HOST"] = "127.0.0.1"
        env["DTF_HEARTBEAT_PORT"] = str(heartbeat_port)
        env["DTF_HEARTBEAT_TIMEOUT_MS"] = str(heartbeat_timeout_ms)
        try:
            from distributed_tensorflow_tpu.runtime import native

            native.load_library()

            def health_factory(world=num_workers):
                # world: the incarnation's member count — a shrunk gang's
                # detector must expect M compact ranks, not N.
                return HeartbeatHealth(
                    heartbeat_port,
                    world,
                    timeout_ms=heartbeat_timeout_ms,
                    stall_timeout_ms=stall_timeout_ms,
                    grace_ms=heartbeat_grace_ms,
                )

        except (ImportError, OSError) as exc:
            # Same degrade set as cluster.bootstrap: a corrupt/wrong-arch
            # .so raises OSError from ctypes, not ImportError.
            print_fn(
                f"elastic: heartbeat detector unavailable ({exc}); "
                "supervising exit codes only"
            )
            env.pop("DTF_HEARTBEAT_HOST")
            env.pop("DTF_HEARTBEAT_PORT")
            env.pop("DTF_HEARTBEAT_TIMEOUT_MS")
    try:
        from distributed_tensorflow_tpu.utils.summary import SummaryWriter

        summary_writer = SummaryWriter(logdir, filename_suffix=".elastic")
    except OSError:  # pragma: no cover — unwritable logdir already raised
        summary_writer = None
    # The driver's event journal (round 10): <logdir>/events.jsonl carries
    # every Restart:/Resize: as a typed event plus the gang's metrics
    # snapshot — tools/obs_report.py replays the run from it.
    from distributed_tensorflow_tpu.observability import EventJournal

    run_id = f"elastic-{os.getpid()}"
    journal = EventJournal.in_dir(logdir, run_id=run_id, world=num_workers)
    # Per-rank worker journals (round 12): workers that bootstrap (or
    # call journal.configure_from_env) land their own
    # <logdir>/events-rank<i>.jsonl next to the driver's events.jsonl —
    # the files obs_report --gang merges into the fleet timeline.
    env["DTF_JOURNAL_DIR"] = logdir
    env["DTF_RUN_ID"] = run_id

    launched: set[int] = set()

    def _worker_env(i: int) -> dict:
        wenv = dict(env)
        wenv["DTF_RANK"] = str(i)  # the member's ORIGINAL id (log convention)
        # Progress watchdog (round 22): the trainer mtime-bumps this file
        # at step/epoch boundaries; SIGUSR1 makes the member dump all
        # stacks to the .stalldump before the watchdog kills it.
        wenv["DTF_HEARTBEAT_FILE"] = heartbeat_file(logdir, i)
        wenv["DTF_STALL_DUMP"] = os.path.join(logdir, f"worker{i}.stalldump")
        return wenv

    def _clear_heartbeat(i: int) -> None:
        # A fresh incarnation must start never-beaten — a stale mtime from
        # the previous life would age straight into a spurious stall
        # verdict (or mask a hung restart with a recent-looking beat).
        try:
            os.remove(heartbeat_file(logdir, i))
        except OSError:
            pass

    def _make_spawn(i: int):
        def _spawn():
            mode = "ab" if i in launched else "wb"
            launched.add(i)
            _clear_heartbeat(i)
            return _spawn_task(
                command, "worker", i, logdir, _worker_env(i), mode=mode
            )

        return _spawn

    def _make_topo_spawn(i: int):
        def _spawn(rank: int, world: int, ranks):
            # A resized incarnation: compact --task_index, the topology in
            # the env (launch.cluster_from_env → ClusterConfig.subset), the
            # log continuing under the member's ORIGINAL id.
            launched.add(i)
            _clear_heartbeat(i)
            tenv = _worker_env(i)
            tenv["DTF_WORLD_SIZE"] = str(world)
            tenv["DTF_WORKER_RANKS"] = ",".join(str(r) for r in ranks)
            return _spawn_task(
                command, "worker", rank, logdir, tenv, mode="ab", log_index=i
            )

        return _spawn

    def _make_heartbeat(i: int):
        def _age() -> float | None:
            # Wall-clock age of the member's last progress beat; None
            # (never judged) while the file doesn't exist yet — startup
            # and first-compile latency never read as a stall.
            try:
                return time.time() - os.path.getmtime(heartbeat_file(logdir, i))
            except OSError:
                return None

        return _age

    def _make_available(i: int):
        def _available():
            return not os.path.exists(lost_marker(logdir, i))

        return _available

    elastic_resize = min_workers is not None and 0 < min_workers < num_workers
    agents = [
        ElasticAgent(
            f"worker{i}",
            _make_spawn(i),
            worker_id=i,
            available_fn=_make_available(i) if elastic_resize else None,
            topo_spawn_fn=_make_topo_spawn(i) if elastic_resize else None,
            heartbeat_fn=_make_heartbeat(i),
        )
        for i in range(num_workers)
    ]
    gang = ElasticGang(
        agents,
        max_restarts=max_restarts,
        backoff=backoff,
        health_factory=health_factory,
        poll_interval=poll_interval,
        min_workers=min_workers if elastic_resize else None,
        rejoin_timeout_s=rejoin_timeout_s,
        independent=independent,
        stall_after_s=stall_after_s,
        print_fn=print_fn,
        summary_writer=summary_writer,
        journal=journal,
    )
    if drive_mode:
        # Scenario driver (demos + integration tests): SIGKILL the highest
        # worker after a delay and mark its host lost; then-replace mode
        # later clears the marker, which the gang reads as a replacement
        # registering (grow trigger).
        victim = num_workers - 1

        def _drive():
            time.sleep(drive_after_s)
            open(lost_marker(logdir, victim), "w").close()
            handle = agents[victim].handle
            if handle is not None:
                try:
                    handle.kill()
                except Exception:  # noqa: BLE001 — already exited
                    pass
            if drive_mode == "kill-then-replace":
                time.sleep(drive_replace_after_s)
                try:
                    os.remove(lost_marker(logdir, victim))
                except OSError:
                    pass

        threading.Thread(target=_drive, daemon=True).start()
    exporter = None
    if metrics_port:
        # Live driver endpoint (round 12): /metrics scrapes the gang's
        # registry (restarts/resizes/world_size/heartbeat ages) while it
        # supervises; /healthz reports the roster the scheduler needs.
        from distributed_tensorflow_tpu.observability import MetricsExporter

        exporter = MetricsExporter(
            gang.metrics,
            port=int(metrics_port),
            health_fn=lambda: {
                "world_size": gang.world_size,
                "restarts": gang.restarts,
                "resizes": gang.resizes,
                "benched": [a.name for a in gang.benched],
            },
        )
        print_fn(f"metrics: http://127.0.0.1:{exporter.start()}/metrics")
    try:
        rc = gang.run()
    finally:
        if exporter is not None:
            exporter.stop()
    journal.close()
    for agent in agents:
        code = agent.poll()
        print_fn(f"{agent.name}: exit {code}")
    return rc


def launch(
    command: list[str],
    num_workers: int,
    num_ps: int = 0,
    logdir: str = "./task_logs",
    env: dict | None = None,
    wait: bool = True,
    *,
    max_restarts: int = 0,
    heartbeat_port: int | None = None,
    heartbeat_timeout_ms: int = 5000,
    # Never-beaten grace before a worker reads as dead. The default (5x
    # timeout via HeartbeatHealth) is 25 s at the default timeout — on a
    # loaded host a cold Python+jax import can exceed that, so raise this
    # (or the timeout) when startup is slow; the integration test uses a
    # 30 s timeout for a 150 s grace.
    heartbeat_grace_ms: int | None = None,
    stall_timeout_ms: int = 0,
    # Progress watchdog (round 22, train/elastic.py): no trainer heartbeat
    # on <logdir>/worker<i>.heartbeat for this long → Stall: verdict,
    # SIGKILL, recovery through the elastic path. Needs NO detector port —
    # the file-mtime path catches the frozen/wedged class (SIGSTOP, hung
    # collective) that exit codes and liveness probes can't see. Size it
    # above the worst-case gap between beats (an epoch + a fresh compile).
    # 0 disables (default).
    stall_after_s: float = 0.0,
    backoff: float = 1.0,
    poll_interval: float = 0.5,
    # Shrink-to-fit resize (round 8; only with max_restarts > 0). None/0
    # disables: the round-7 fixed-size gang.
    min_workers: int | None = None,
    rejoin_timeout_s: float = 30.0,
    # Independent member supervision (round 17, train/elastic.py): a
    # failed member relaunches ALONE while the others keep running — for
    # collective-free gangs (the stale-tolerant DiLoCo mailbox). Does
    # not compose with min_workers resizing.
    independent: bool = False,
    drive_mode: str | None = None,
    drive_after_s: float = 8.0,
    drive_replace_after_s: float = 10.0,
    # Live /metrics + /healthz on the elastic driver (round 12,
    # observability/exporter.py). None/0 = nothing listens.
    metrics_port: int | None = None,
    print_fn=print,
) -> int:
    if max_restarts > 0 and not wait:
        # Supervision IS waiting: silently spawning unsupervised workers
        # would drop the requested restart budget on the floor.
        raise ValueError("max_restarts > 0 requires wait=True (the elastic "
                         "agent supervises the gang to completion)")
    if min_workers and min_workers > num_workers:
        raise ValueError(
            f"min_workers={min_workers} exceeds num_workers={num_workers}"
        )
    if min_workers and not max_restarts:
        raise ValueError(
            "min_workers needs max_restarts > 0 (resizing is a relaunch — "
            "a one-shot gang has no budget to relaunch with)"
        )
    if drive_mode not in (None, "", "none", "kill-without-replace",
                          "kill-then-replace"):
        raise ValueError(
            f"unknown drive_mode {drive_mode!r}; use "
            "kill-without-replace or kill-then-replace"
        )
    if drive_mode in ("", "none"):
        drive_mode = None
    os.makedirs(logdir, exist_ok=True)
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    # ps tasks no-op and exit on TPU: launch one-shot, never supervised —
    # a clean ps exit must not read as a gang failure, and a gang restart
    # must not respawn them.
    ps_procs = [
        ("ps%d" % i, _spawn_task(command, "ps", i, logdir, base_env))
        for i in range(num_ps)
    ]
    if max_restarts > 0:
        rc = _launch_elastic(
            command,
            num_workers,
            logdir,
            base_env,
            max_restarts=max_restarts,
            heartbeat_port=heartbeat_port,
            heartbeat_timeout_ms=heartbeat_timeout_ms,
            heartbeat_grace_ms=heartbeat_grace_ms,
            stall_timeout_ms=stall_timeout_ms,
            stall_after_s=stall_after_s,
            backoff=backoff,
            poll_interval=poll_interval,
            min_workers=min_workers,
            rejoin_timeout_s=rejoin_timeout_s,
            independent=independent,
            drive_mode=drive_mode,
            drive_after_s=drive_after_s,
            drive_replace_after_s=drive_replace_after_s,
            metrics_port=metrics_port,
            print_fn=print_fn,
        )
        for name, p in ps_procs:
            print_fn(f"{name}: exit {p.wait()}")
        return rc
    # Fail-stop path (max_restarts=0): the pre-round-7 behavior, unchanged —
    # wait for every task, non-zero if any worker failed.
    procs = ps_procs + [
        ("worker%d" % i, _spawn_task(command, "worker", i, logdir, base_env))
        for i in range(num_workers)
    ]
    if not wait:
        return 0
    rc = 0
    for name, p in procs:
        code = p.wait()
        print_fn(f"{name}: exit {code}")
        if code != 0 and name.startswith("worker"):
            rc = 1
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workers", type=int, required=True)
    parser.add_argument("--ps", type=int, default=0)
    parser.add_argument("--logdir", type=str, default="./task_logs")
    # CLI defaults come from the DTF_* env knobs (launch.config_from_env /
    # cluster_from_env's pod-scheduler surface): a scheduler that sets
    # DTF_MAX_RESTARTS=3 arms the elastic driver with no flag changes.
    parser.add_argument(
        "--max-restarts",
        type=int,
        default=int(os.environ.get("DTF_MAX_RESTARTS", "0") or 0),
        help="elastic gang-restart budget (train/elastic.py); 0 = the "
        "one-shot fail-stop launcher (default: $DTF_MAX_RESTARTS or 0)",
    )
    parser.add_argument(
        "--heartbeat-port",
        type=int,
        default=int(os.environ.get("DTF_HEARTBEAT_PORT", "0") or 0) or None,
        help="driver-hosted UDP failure detector port (workers are pointed "
        "at it via DTF_HEARTBEAT_HOST/_PORT); only used with --max-restarts "
        "(default: $DTF_HEARTBEAT_PORT)",
    )
    parser.add_argument(
        "--heartbeat-timeout-ms",
        type=int,
        default=int(os.environ.get("DTF_HEARTBEAT_TIMEOUT_MS", "5000") or 5000),
    )
    parser.add_argument(
        "--heartbeat-grace-ms",
        type=int,
        default=None,
        help="never-beaten grace before a worker reads as dead (default: "
        "5x the timeout); raise it when cold startup — imports, jax "
        "rendezvous, first compile — outlasts that window",
    )
    parser.add_argument(
        "--stall-timeout-ms",
        type=int,
        default=int(os.environ.get("DTF_STALL_TIMEOUT_MS", "0") or 0),
        help="recover a worker whose heartbeats flow but whose progress "
        "counter is frozen past this window (0 disables; default: "
        "$DTF_STALL_TIMEOUT_MS)",
    )
    parser.add_argument(
        "--stall-after-s",
        type=float,
        default=float(os.environ.get("DTF_STALL_AFTER_S", "0") or 0),
        help="file-based progress watchdog (round 22): kill and recover a "
        "worker whose <logdir>/worker<i>.heartbeat has not advanced for "
        "this long — catches the frozen/wedged class without any detector "
        "port; size above the worst epoch+compile gap (0 disables; "
        "default: $DTF_STALL_AFTER_S)",
    )
    parser.add_argument("--backoff", type=float, default=1.0)
    parser.add_argument(
        "--min-workers",
        type=int,
        default=int(os.environ.get("DTF_MIN_WORKERS", "0") or 0),
        help="shrink-to-fit floor (round 8): a lost-and-unreplaced worker "
        "shrinks the gang down to this size instead of restart-looping; "
        "0 disables resizing (default: $DTF_MIN_WORKERS or 0)",
    )
    parser.add_argument(
        "--rejoin-timeout-s",
        type=float,
        default=float(os.environ.get("DTF_REJOIN_TIMEOUT_S", "30") or 30),
        help="how long a failed worker's slot may wait for a replacement "
        "(delete <logdir>/worker<i>.lost to register one) before the gang "
        "resizes without it (default: $DTF_REJOIN_TIMEOUT_S or 30)",
    )
    parser.add_argument(
        "--independent",
        action="store_true",
        help="relaunch failed members ALONE instead of restarting the "
        "gang (round 17 — collective-free gangs like the stale-tolerant "
        "DiLoCo mailbox; needs --max-restarts, excludes --min-workers)",
    )
    parser.add_argument(
        "--drive-mode",
        choices=("none", "kill-without-replace", "kill-then-replace"),
        default="none",
        help="scenario driver: SIGKILL the highest worker after "
        "--drive-after-s and mark its host lost; then-replace clears the "
        "marker after --drive-replace-after-s so the gang regrows",
    )
    parser.add_argument("--drive-after-s", type=float, default=8.0)
    parser.add_argument("--drive-replace-after-s", type=float, default=10.0)
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=int(os.environ.get("DTF_METRICS_PORT", "0") or 0) or None,
        help="serve the elastic driver's live /metrics (Prometheus) and "
        "/healthz on this port while the gang runs (observability/"
        "exporter.py); 0/unset disables (default: $DTF_METRICS_PORT)",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- command to launch per task")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("missing command after --")
    return launch(
        command,
        args.workers,
        args.ps,
        args.logdir,
        max_restarts=args.max_restarts,
        heartbeat_port=args.heartbeat_port,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        heartbeat_grace_ms=args.heartbeat_grace_ms,
        stall_timeout_ms=args.stall_timeout_ms,
        stall_after_s=args.stall_after_s,
        backoff=args.backoff,
        min_workers=args.min_workers or None,
        rejoin_timeout_s=args.rejoin_timeout_s,
        independent=args.independent,
        drive_mode=args.drive_mode,
        drive_after_s=args.drive_after_s,
        drive_replace_after_s=args.drive_replace_after_s,
        metrics_port=args.metrics_port,
    )


if __name__ == "__main__":
    sys.exit(main())
