"""Local cluster launcher — the reference's nohup-per-task workflow, automated
and (round 7) supervised.

The reference ran every topology by hand-launching one process per task::

    nohup python tfdist_between.py --job_name=ps --task_index=0 > ps.log 2>&1 &
    nohup python tfdist_between.py --job_name=worker --task_index=0 > w0.log ...

(reference README.md:34-35, 58-60; C17 in SURVEY.md §2). This tool does the
same thing in one command, against any script that accepts the standard
``--job_name/--task_index`` flags::

    python -m distributed_tensorflow_tpu.tools.launch_local \
        --workers 2 --ps 1 --logdir ./task_logs -- python examples/between_sync.py

One OS process per task, stdout/stderr redirected to ``<logdir>/<role><i>.log``
exactly like the nohup recipe, non-zero exit if any worker fails. ps tasks
are launched too (they no-op and exit, preserving launcher compatibility).

``--max-restarts N`` (round 7) upgrades the one-shot spawner into the
elastic agent's driver (train/elastic.py): each worker gets a supervising
:class:`ElasticAgent`; a member that exits non-zero — or, with
``--heartbeat-port``, goes heartbeat-dead or live-but-stalled past
``--stall-timeout-ms`` — triggers a GANG restart: every worker is killed
and relaunched after a jittered exponential backoff, at most N times, with
a structured ``Restart:`` line and a ``restart`` tfevents scalar per event.
Relaunched workers re-bootstrap ``jax.distributed`` (bounded retried
initialize, ``cluster.bounded_initialize``) and resume from the newest
valid checkpoint — arm ``DTF_CHECKPOINT`` so there is something to resume.
The driver hosts the heartbeat detector itself (out-of-band of the job)
and points the workers at it via ``DTF_HEARTBEAT_HOST``/``_PORT``;
``max_restarts=0`` (default) preserves the old fail-stop behavior exactly.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def _spawn_task(
    command: list[str],
    role: str,
    index: int,
    logdir: str,
    env: dict,
    mode: str = "wb",
):
    """One task process, stdout+stderr to ``<logdir>/<role><i>.log``. The
    first incarnation truncates (the pre-round-7 behavior, unchanged); a
    gang RELAUNCH passes ``mode="ab"`` so the restarted incarnation's log
    continues the same file instead of erasing the failure it is
    recovering from."""
    log_path = os.path.join(logdir, f"{role}{index}.log")
    f = open(log_path, mode)
    try:
        return subprocess.Popen(
            command + [f"--job_name={role}", f"--task_index={index}"],
            stdout=f,
            stderr=subprocess.STDOUT,
            env=env,
        )
    finally:
        # Popen inherited the descriptor; closing ours leaks nothing and a
        # relaunch reopens fresh (no shared offsets across incarnations).
        f.close()


def _launch_elastic(
    command: list[str],
    num_workers: int,
    logdir: str,
    base_env: dict,
    *,
    max_restarts: int,
    heartbeat_port: int | None,
    heartbeat_timeout_ms: int,
    heartbeat_grace_ms: int | None,
    stall_timeout_ms: int,
    backoff: float,
    poll_interval: float,
    print_fn=print,
) -> int:
    from distributed_tensorflow_tpu.train.elastic import (
        ElasticAgent,
        ElasticGang,
        HeartbeatHealth,
    )

    env = dict(base_env)
    health_factory = None
    summary_writer = None
    if heartbeat_port:
        # The driver hosts the detector (out-of-band of the job); workers
        # learn where to beat from the env, chief included
        # (cluster.bootstrap heartbeat_host mode).
        env["DTF_HEARTBEAT_HOST"] = "127.0.0.1"
        env["DTF_HEARTBEAT_PORT"] = str(heartbeat_port)
        env["DTF_HEARTBEAT_TIMEOUT_MS"] = str(heartbeat_timeout_ms)
        try:
            from distributed_tensorflow_tpu.runtime import native

            native.load_library()

            def health_factory():
                return HeartbeatHealth(
                    heartbeat_port,
                    num_workers,
                    timeout_ms=heartbeat_timeout_ms,
                    stall_timeout_ms=stall_timeout_ms,
                    grace_ms=heartbeat_grace_ms,
                )

        except (ImportError, OSError) as exc:
            # Same degrade set as cluster.bootstrap: a corrupt/wrong-arch
            # .so raises OSError from ctypes, not ImportError.
            print_fn(
                f"elastic: heartbeat detector unavailable ({exc}); "
                "supervising exit codes only"
            )
            env.pop("DTF_HEARTBEAT_HOST")
            env.pop("DTF_HEARTBEAT_PORT")
            env.pop("DTF_HEARTBEAT_TIMEOUT_MS")
    try:
        from distributed_tensorflow_tpu.utils.summary import SummaryWriter

        summary_writer = SummaryWriter(logdir, filename_suffix=".elastic")
    except OSError:  # pragma: no cover — unwritable logdir already raised
        summary_writer = None

    launched: set[int] = set()

    def _make_spawn(i: int):
        def _spawn():
            mode = "ab" if i in launched else "wb"
            launched.add(i)
            return _spawn_task(command, "worker", i, logdir, env, mode=mode)

        return _spawn

    agents = [
        ElasticAgent(f"worker{i}", _make_spawn(i), worker_id=i)
        for i in range(num_workers)
    ]
    gang = ElasticGang(
        agents,
        max_restarts=max_restarts,
        backoff=backoff,
        health_factory=health_factory,
        poll_interval=poll_interval,
        print_fn=print_fn,
        summary_writer=summary_writer,
    )
    rc = gang.run()
    for agent in agents:
        code = agent.poll()
        print_fn(f"{agent.name}: exit {code}")
    return rc


def launch(
    command: list[str],
    num_workers: int,
    num_ps: int = 0,
    logdir: str = "./task_logs",
    env: dict | None = None,
    wait: bool = True,
    *,
    max_restarts: int = 0,
    heartbeat_port: int | None = None,
    heartbeat_timeout_ms: int = 5000,
    # Never-beaten grace before a worker reads as dead. The default (5x
    # timeout via HeartbeatHealth) is 25 s at the default timeout — on a
    # loaded host a cold Python+jax import can exceed that, so raise this
    # (or the timeout) when startup is slow; the integration test uses a
    # 30 s timeout for a 150 s grace.
    heartbeat_grace_ms: int | None = None,
    stall_timeout_ms: int = 0,
    backoff: float = 1.0,
    poll_interval: float = 0.5,
    print_fn=print,
) -> int:
    if max_restarts > 0 and not wait:
        # Supervision IS waiting: silently spawning unsupervised workers
        # would drop the requested restart budget on the floor.
        raise ValueError("max_restarts > 0 requires wait=True (the elastic "
                         "agent supervises the gang to completion)")
    os.makedirs(logdir, exist_ok=True)
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    # ps tasks no-op and exit on TPU: launch one-shot, never supervised —
    # a clean ps exit must not read as a gang failure, and a gang restart
    # must not respawn them.
    ps_procs = [
        ("ps%d" % i, _spawn_task(command, "ps", i, logdir, base_env))
        for i in range(num_ps)
    ]
    if max_restarts > 0:
        rc = _launch_elastic(
            command,
            num_workers,
            logdir,
            base_env,
            max_restarts=max_restarts,
            heartbeat_port=heartbeat_port,
            heartbeat_timeout_ms=heartbeat_timeout_ms,
            heartbeat_grace_ms=heartbeat_grace_ms,
            stall_timeout_ms=stall_timeout_ms,
            backoff=backoff,
            poll_interval=poll_interval,
            print_fn=print_fn,
        )
        for name, p in ps_procs:
            print_fn(f"{name}: exit {p.wait()}")
        return rc
    # Fail-stop path (max_restarts=0): the pre-round-7 behavior, unchanged —
    # wait for every task, non-zero if any worker failed.
    procs = ps_procs + [
        ("worker%d" % i, _spawn_task(command, "worker", i, logdir, base_env))
        for i in range(num_workers)
    ]
    if not wait:
        return 0
    rc = 0
    for name, p in procs:
        code = p.wait()
        print_fn(f"{name}: exit {code}")
        if code != 0 and name.startswith("worker"):
            rc = 1
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workers", type=int, required=True)
    parser.add_argument("--ps", type=int, default=0)
    parser.add_argument("--logdir", type=str, default="./task_logs")
    # CLI defaults come from the DTF_* env knobs (launch.config_from_env /
    # cluster_from_env's pod-scheduler surface): a scheduler that sets
    # DTF_MAX_RESTARTS=3 arms the elastic driver with no flag changes.
    parser.add_argument(
        "--max-restarts",
        type=int,
        default=int(os.environ.get("DTF_MAX_RESTARTS", "0") or 0),
        help="elastic gang-restart budget (train/elastic.py); 0 = the "
        "one-shot fail-stop launcher (default: $DTF_MAX_RESTARTS or 0)",
    )
    parser.add_argument(
        "--heartbeat-port",
        type=int,
        default=int(os.environ.get("DTF_HEARTBEAT_PORT", "0") or 0) or None,
        help="driver-hosted UDP failure detector port (workers are pointed "
        "at it via DTF_HEARTBEAT_HOST/_PORT); only used with --max-restarts "
        "(default: $DTF_HEARTBEAT_PORT)",
    )
    parser.add_argument(
        "--heartbeat-timeout-ms",
        type=int,
        default=int(os.environ.get("DTF_HEARTBEAT_TIMEOUT_MS", "5000") or 5000),
    )
    parser.add_argument(
        "--heartbeat-grace-ms",
        type=int,
        default=None,
        help="never-beaten grace before a worker reads as dead (default: "
        "5x the timeout); raise it when cold startup — imports, jax "
        "rendezvous, first compile — outlasts that window",
    )
    parser.add_argument(
        "--stall-timeout-ms",
        type=int,
        default=int(os.environ.get("DTF_STALL_TIMEOUT_MS", "0") or 0),
        help="recover a worker whose heartbeats flow but whose progress "
        "counter is frozen past this window (0 disables; default: "
        "$DTF_STALL_TIMEOUT_MS)",
    )
    parser.add_argument("--backoff", type=float, default=1.0)
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- command to launch per task")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("missing command after --")
    return launch(
        command,
        args.workers,
        args.ps,
        args.logdir,
        max_restarts=args.max_restarts,
        heartbeat_port=args.heartbeat_port,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        heartbeat_grace_ms=args.heartbeat_grace_ms,
        stall_timeout_ms=args.stall_timeout_ms,
        backoff=args.backoff,
    )


if __name__ == "__main__":
    sys.exit(main())
