"""On-chip flash-attention parity record: Mosaic kernels vs dense XLA.

The fast test suite proves the Pallas kernels against the dense oracle in
*interpreter* mode (conftest forces CPU); the Mosaic-compiled path on the
real chip was verified interactively in round 2 but recorded only as a
commit-message claim (VERDICT round-2 weak #7). This tool makes that
verification a regenerable artifact: it runs forward AND gradient parity
for the full feature matrix — causal, sliding window (both sides of the
banding crossover), GQA, key-padding (kv_lens), and the ring-composition
``offset`` — against ``dense_attention`` on whatever backend it's launched
on, and emits one JSON line with per-case max errors and pass/fail.
Round 13 adds a ``fused-vs-split:*`` row per case: the one-pass fused
dq+dk+dv backward (the new default) against the two-kernel split on the
same forward, so the on-chip record covers the fused kernel explicitly.
Round 18 adds ``decode-fused-vs-xla:*`` rows: the fused Pallas
decode-step kernel (ops/pallas_decode.py) against the unrolled XLA
decode engine over a short greedy decode — max logit error across
steps plus the greedy-token agreement fraction, per serving-config
feature (dense / GQA / rolling window / paged / int8 / fp8 KV). The
round-3 lesson applies to these too: the CPU interpreter tolerates
Mosaic-only bugs, so the rows only count as a kernel proof when the
row says Mosaic.
Round 20: the round-18 engine is now ``decode_engine="pallas-layer"``
(the case names keep their committed round-18 ids); the new
``decode-mega-vs-xla:*`` rows run the multi-layer megakernel
(``decode_engine="pallas"``, one launch per token, in-kernel aliased
cache commit) over the same matrix, and ``verify-fused-vs-xla:*`` rows
prove the fused speculation-verify kernel (``GPTLM.verify_paged``)
against the XLA extend path — logit error + argmax agreement on the
valid suffix rows AND a bitwise cache/pool check (the commit contract).
Rows now carry per-row ``device``/``mode`` provenance and
``--write-docs`` MERGES into the committed record: a Mosaic row is
never overwritten by an interpreter rerun, so the round-2 on-chip
record survives off-chip regenerations while new cases land beside it
tagged with the device that actually ran them.

Usage (on the TPU)::

    python -m distributed_tensorflow_tpu.tools.attention_parity \
        --write-docs      # regenerates docs/benchmarks/attention_parity.md

Tolerances are bf16-scale (the kernels do f32 softmax math over bf16 MXU
dots, like XLA's default) — rtol 2e-2 / atol 2e-2 on values whose scale
is O(1); gradients compare at the same bar.
"""

from __future__ import annotations

import argparse
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

RTOL, ATOL = 2e-2, 2e-2


def _case(name, *, l=512, h=4, hkv=None, d=64, causal=True, window=None,
          kv_lens=None, offset=0, block=None):
    return dict(
        name=name, l=l, h=h, hkv=hkv or h, d=d, causal=causal, window=window,
        kv_lens=kv_lens, offset=offset, block=block,
    )


CASES = [
    _case("causal"),
    _case("noncausal", causal=False),
    _case("causal-block128", block=128),
    _case("window-below-banding", window=256, l=512),  # 4W > L: banding off
    _case("window-banded", window=64, l=1024),  # 4W <= L: banded index maps
    _case("gqa", h=8, hkv=2),
    _case("gqa-window", h=8, hkv=2, window=128, l=1024),
    _case("kv-lens", kv_lens=(301, 444)),
    _case("kv-lens-gqa", h=8, hkv=2, kv_lens=(301, 444)),
    _case("offset-shifted-band", window=96, offset=256, l=512),
]


def _decode_case(name, *, engine="pallas", kv_dtype="bf16", heads=4,
                 kv_heads=None, window=None, paged=False):
    return dict(
        name=name, engine=engine, kv_dtype=kv_dtype, heads=heads,
        kv_heads=kv_heads or heads, window=window, paged=paged,
    )


DECODE_CASES = [
    # Round-18 rows: the per-layer kernel (its engine id became
    # "pallas-layer" in round 20; the committed case names stay).
    _decode_case("decode-fused-vs-xla:dense-bf16", engine="pallas-layer"),
    _decode_case(
        "decode-fused-vs-xla:dense-int8", engine="pallas-layer",
        kv_dtype="int8",
    ),
    _decode_case(
        "decode-fused-vs-xla:dense-fp8", engine="pallas-layer",
        kv_dtype="fp8",
    ),
    _decode_case(
        "decode-fused-vs-xla:gqa", engine="pallas-layer", heads=8,
        kv_heads=2,
    ),
    _decode_case(
        "decode-fused-vs-xla:window-rolling", engine="pallas-layer",
        window=16,
    ),
    _decode_case(
        "decode-fused-vs-xla:paged-int8", engine="pallas-layer",
        kv_dtype="int8", paged=True,
    ),
    # Round-20 rows: the multi-layer megakernel over the same matrix.
    _decode_case("decode-mega-vs-xla:dense-bf16"),
    _decode_case("decode-mega-vs-xla:dense-int8", kv_dtype="int8"),
    _decode_case("decode-mega-vs-xla:dense-fp8", kv_dtype="fp8"),
    _decode_case("decode-mega-vs-xla:gqa", heads=8, kv_heads=2),
    _decode_case("decode-mega-vs-xla:window-rolling", window=16),
    _decode_case(
        "decode-mega-vs-xla:paged-int8", kv_dtype="int8", paged=True
    ),
]


VERIFY_CASES = [
    _decode_case("verify-fused-vs-xla:bf16", paged=True),
    _decode_case("verify-fused-vs-xla:int8", kv_dtype="int8", paged=True),
    _decode_case("verify-fused-vs-xla:fp8", kv_dtype="fp8", paged=True),
    _decode_case(
        "verify-fused-vs-xla:gqa-int8", kv_dtype="int8", heads=8,
        kv_heads=2, paged=True,
    ),
    _decode_case(
        "verify-fused-vs-xla:window-int8", kv_dtype="int8", window=16,
        paged=True,
    ),
]


def _decode_model_and_cache(c: dict):
    import numpy as np

    from distributed_tensorflow_tpu.models.gpt import GPTLM

    m = GPTLM(
        vocab_size=97, max_len=64, model_dim=32, num_heads=c["heads"],
        num_kv_heads=c["kv_heads"], num_layers=2, pos_embedding="rope",
        window=c["window"],
    )
    params = m.init(seed=1)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 97, (3, 8)), jnp.int32)
    lens = jnp.asarray([8, 5, 3], jnp.int32)
    admit = jnp.ones((3,), bool)
    if c["paged"]:
        cache = m.empty_paged_cache(3, 24, block_size=8, kv_dtype=c["kv_dtype"])
        tables = np.zeros((3, m.paged_blocks_per_slot(8)), np.int32)
        nb = m.paged_blocks_per_slot(8)
        for s in range(3):
            tables[s] = np.arange(1 + s * nb, 1 + (s + 1) * nb) % 24
        cache = cache._replace(block_tables=jnp.asarray(tables))
        _, cache = m.extend_paged(
            params, cache, toks, lens, jnp.zeros((3,), jnp.int32), admit
        )
        cache = cache._replace(lengths=lens)
    else:
        cache = m.empty_slot_cache(3, c["kv_dtype"])
        _, cache = m.prefill_slots(params, cache, toks, lens, admit)
    return m, params, cache


def run_decode_case(c: dict) -> dict:
    """One serving config's Pallas-vs-XLA decode parity: prefill three
    ragged prompts into slots, then 8 greedy decode steps with BOTH
    engines fed the XLA engine's token stream (teacher-forced) — so
    every step scores the same prefix and the max logit error stays a
    kernel-parity measurement even after a budgeted argmax flip (self-
    fed streams would diverge at the first flip and the error metric
    would measure different prefixes, not the kernel). Token agreement
    is the per-step argmax match under those identical prefixes; ``ok``
    needs logit error under the shared tolerance bar and ≥ 90% token
    agreement (bf16 compute — flips at near-ties are the budgeted
    residual; tests/test_pallas_decode.py pins the tight f32
    contract). ``c["engine"]`` selects the kernel tier: "pallas-layer"
    (round 18, one launch per block) or "pallas" (round 20 megakernel,
    one launch per token)."""
    import numpy as np

    m, params, cache = _decode_model_and_cache(c)
    decode = m.decode_paged if c["paged"] else m.decode_slots
    tok = jnp.asarray([1, 2, 3], jnp.int32)
    cx = cp = cache
    tx = tok
    steps, agree, err = 8, 0, 0.0
    for _ in range(steps):
        lx, cx = decode(params, tx, cx, engine="xla")
        lp, cp = decode(params, tx, cp, engine=c["engine"])
        err = max(err, float(jnp.max(jnp.abs(
            lx.astype(jnp.float32) - lp.astype(jnp.float32)
        ))))
        nx = jnp.argmax(lx, -1).astype(jnp.int32)
        npal = jnp.argmax(lp, -1).astype(jnp.int32)
        agree += int((np.asarray(nx) == np.asarray(npal)).sum())
        tx = nx  # teacher-force the XLA stream into BOTH engines
    tok_match = agree / (steps * 3)
    tol = ATOL + RTOL
    return {
        "case": c["name"],
        "fwd_max_err": round(err, 5),
        "tok_match": round(tok_match, 4),
        "ok": bool(err < tol and tok_match >= 0.9),
    }


def run_verify_case(c: dict) -> dict:
    """Fused speculation-verify parity (round 20): score a 4-token
    draft suffix per slot with ``GPTLM.verify_paged`` under both
    engines ("xla" delegates to the extend path; "pallas" launches the
    fused verify kernel). Logit error and argmax agreement are measured
    on the VALID suffix rows of admitted slots only; the committed
    cache — payload AND quantization scales — must match the XLA
    extend's scatter bit-for-bit on the payload (scales compare at f32
    reassociation tolerance), because greedy-exact acceptance rides on
    the verified suffix being the one the cache remembers."""
    import numpy as np

    m, params, cache = _decode_model_and_cache(c)
    rng = np.random.default_rng(3)
    suffix = jnp.asarray(rng.integers(0, 97, (3, 4)), jnp.int32)
    slens = jnp.asarray([4, 3, 4], jnp.int32)
    admit = jnp.asarray([True, True, False])
    lx, cvx = m.verify_paged(
        params, cache, suffix, slens, cache.lengths, admit, engine="xla"
    )
    lp, cvp = m.verify_paged(
        params, cache, suffix, slens, cache.lengths, admit,
        engine="pallas",
    )
    valid = (
        (jnp.arange(suffix.shape[1])[None, :] < slens[:, None])
        & admit[:, None]
    )
    err = float(jnp.max(jnp.where(
        valid[..., None],
        jnp.abs(lx.astype(jnp.float32) - lp.astype(jnp.float32)),
        0.0,
    )))
    nx = np.asarray(jnp.argmax(lx, -1))
    npal = np.asarray(jnp.argmax(lp, -1))
    vmask = np.asarray(valid)
    tok_match = float((nx == npal)[vmask].mean())
    cache_ok = bool(jnp.all(cvx.k == cvp.k)) and bool(
        jnp.all(cvx.v == cvp.v)
    )
    if cvx.k_scale is not None:
        cache_ok = cache_ok and bool(
            jnp.allclose(cvx.k_scale, cvp.k_scale, atol=1e-6)
        ) and bool(jnp.allclose(cvx.v_scale, cvp.v_scale, atol=1e-6))
    tol = ATOL + RTOL
    return {
        "case": c["name"],
        "fwd_max_err": round(err, 5),
        "tok_match": round(tok_match, 4),
        "cache_bitwise": cache_ok,
        "ok": bool(err < tol and tok_match >= 0.9 and cache_ok),
    }


def run_case(c: dict) -> dict:
    from distributed_tensorflow_tpu.ops.pallas_attention import flash_attention
    from distributed_tensorflow_tpu.ops.ring_attention import dense_attention

    b = 2
    kq, kk, kv, kc = jax.random.split(jax.random.key(7), 4)
    q = jax.random.normal(kq, (b, c["l"], c["h"], c["d"]), jnp.bfloat16)
    k = jax.random.normal(kk, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    v = jax.random.normal(kv, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    lens = (
        None if c["kv_lens"] is None else jnp.asarray(c["kv_lens"], jnp.int32)
    )
    kw = dict(
        causal=c["causal"], window=c["window"], kv_lens=lens,
        block_q=c["block"], block_k=c["block"],
    )
    cot = jax.random.normal(kc, q.shape, jnp.float32)

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, offset=c["offset"], **kw)

    def dense_fn(q, k, v):
        # dense_attention has no offset — emulate the shifted band by
        # masking scores directly (the definition offset implements).
        if c["offset"]:
            qf = q.astype(jnp.float32)
            kf = k.astype(jnp.float32)
            kf, vf = kf, v.astype(jnp.float32)
            from distributed_tensorflow_tpu.ops.ring_attention import repeat_kv

            kf, vf = repeat_kv(kf, vf, q.shape[2])
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(c["d"])
            diff = (
                jnp.arange(c["l"])[:, None] + c["offset"]
                - jnp.arange(c["l"])[None, :]
            )
            mask = diff >= 0
            if c["window"] is not None:
                mask &= diff < c["window"]
            s = jnp.where(mask[None, None], s, -1e30)
            w = jax.nn.softmax(s, axis=-1)
            # Fully-masked rows (offset pushes the whole band past the
            # sequence end): match the kernel's zero-output convention
            # instead of softmax-of-constants garbage, so outputs AND
            # gradients are comparable everywhere.
            row_valid = mask.any(axis=-1)[None, None, :, None]
            w = jnp.where(row_valid, w, 0.0)
            out = jnp.einsum("bhqk,bkhd->bqhd", w, vf)
            return out.astype(q.dtype)
        return dense_attention(
            q, k, v, causal=c["causal"], window=c["window"], kv_lens=lens
        )

    f_out = jax.jit(flash_fn)(q, k, v)
    d_out = jax.jit(dense_fn)(q, k, v)

    def gsum(fn):
        return jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) * cot),
                argnums=(0, 1, 2),
            )
        )(q, k, v)

    g_f, g_d = gsum(flash_fn), gsum(dense_fn)

    # Compare only rows that are not fully masked (padded queries whose
    # whole window lies beyond kv_len are documented garbage on both
    # sides, with different conventions).
    def err(a, b):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        return float(np.max(np.abs(a - b)))

    fwd_err = err(f_out, d_out)
    grad_errs = {n: err(a, b) for n, a, b in zip("qkv", g_f, g_d)}
    tol = ATOL + RTOL  # values are O(1)
    ok = fwd_err < tol and all(e < tol for e in grad_errs.values())
    return {
        "case": c["name"],
        "fwd_max_err": round(fwd_err, 5),
        "dq_max_err": round(grad_errs["q"], 5),
        "dk_max_err": round(grad_errs["k"], 5),
        "dv_max_err": round(grad_errs["v"], 5),
        "ok": bool(ok),
    }


def run_fused_split_case(c: dict) -> dict:
    """Round-13 rows: the fused one-pass backward against the two-kernel
    split on the SAME flash forward — the on-chip record for the new
    kernel (the main rows already run the fused default against dense;
    this isolates fused-vs-split, which should be ~bitwise since both
    accumulate in f32). The round-3 lesson applies verbatim: the CPU
    interpreter tolerates Mosaic-only bugs, so these rows only count
    when the header says Mosaic."""
    from distributed_tensorflow_tpu.ops.pallas_attention import flash_attention

    b = 2
    kq, kk, kv, kc = jax.random.split(jax.random.key(7), 4)
    q = jax.random.normal(kq, (b, c["l"], c["h"], c["d"]), jnp.bfloat16)
    k = jax.random.normal(kk, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    v = jax.random.normal(kv, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    lens = (
        None if c["kv_lens"] is None else jnp.asarray(c["kv_lens"], jnp.int32)
    )
    cot = jax.random.normal(kc, q.shape, jnp.float32)
    kw = dict(
        causal=c["causal"], window=c["window"], kv_lens=lens,
        offset=c["offset"], block_q=c["block"], block_k=c["block"],
    )

    def gsum(fused):
        return jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, fused=fused, **kw).astype(
                        jnp.float32
                    )
                    * cot
                ),
                argnums=(0, 1, 2),
            )
        )(q, k, v)

    g_f, g_s = gsum(True), gsum(False)

    def err(a, b):
        return float(np.max(np.abs(np.asarray(a, np.float32) - np.asarray(b, np.float32))))

    grad_errs = {n: err(a, b) for n, a, b in zip("qkv", g_f, g_s)}
    tol = ATOL + RTOL
    ok = all(e < tol for e in grad_errs.values())
    return {
        "case": f"fused-vs-split:{c['name']}",
        "fwd_max_err": 0.0,  # same forward kernel by construction
        "dq_max_err": round(grad_errs["q"], 5),
        "dk_max_err": round(grad_errs["k"], 5),
        "dv_max_err": round(grad_errs["v"], 5),
        "ok": bool(ok),
    }


def _case_order() -> list[str]:
    order = []
    for c in CASES:
        order += [c["name"], f"fused-vs-split:{c['name']}"]
    order += [c["name"] for c in DECODE_CASES]
    order += [c["name"] for c in VERIFY_CASES]
    return order


def merge_rows(new_rows: list[dict], old_payload: dict | None) -> list[dict]:
    """Per-row provenance merge (round 20): committed rows without a
    ``device``/``mode`` tag inherit the committed payload's header (the
    round-2 record predates per-row tags); a new row replaces the
    committed one UNLESS that would downgrade a Mosaic row to an
    interpreter rerun — the on-chip proof is the scarce artifact, an
    off-chip regeneration must never erase it. Rows are ordered by the
    current case list, unknown (retired) committed cases trail."""
    merged: dict[str, dict] = {}
    if old_payload:
        old_mode = (
            "Mosaic" if old_payload.get("backend") == "tpu"
            else "interpreter"
        )
        for r in old_payload.get("rows", []):
            r = dict(r)
            r.setdefault("device", old_payload.get("device", "?"))
            r.setdefault("mode", old_mode)
            merged[r["case"]] = r
    for r in new_rows:
        prev = merged.get(r["case"])
        if (
            prev is not None
            and prev.get("mode") == "Mosaic"
            and r.get("mode") != "Mosaic"
        ):
            continue
        merged[r["case"]] = r
    order = {name: i for i, name in enumerate(_case_order())}
    return sorted(
        merged.values(),
        key=lambda r: (order.get(r["case"], len(order)), r["case"]),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--write-docs", action="store_true")
    ap.add_argument("--cases", nargs="+", default=None)
    args = ap.parse_args(argv)
    known = (
        {c["name"] for c in CASES}
        | {c["name"] for c in DECODE_CASES}
        | {c["name"] for c in VERIFY_CASES}
    )
    if args.cases:
        unknown = set(args.cases) - known
        if unknown:
            # A typo must not yield a vacuously-green (empty) record.
            ap.error(
                f"unknown case(s) {sorted(unknown)}; known: {sorted(known)}"
            )
    device = jax.devices()[0].device_kind
    backend = jax.default_backend()
    mode = "Mosaic" if backend == "tpu" else "interpreter"
    rows = []
    for c in CASES:
        if args.cases and c["name"] not in args.cases:
            continue
        for runner, label in ((run_case, c["name"]),
                              (run_fused_split_case, f"fused-vs-split:{c['name']}")):
            try:
                rows.append(runner(c))
            except Exception as exc:  # noqa: BLE001
                rows.append(
                    {"case": label, "ok": False,
                     "error": f"{type(exc).__name__}: {exc}"[:200]}
                )
    for cases, runner in ((DECODE_CASES, run_decode_case),
                          (VERIFY_CASES, run_verify_case)):
        for c in cases:
            if args.cases and c["name"] not in args.cases:
                continue
            try:
                rows.append(runner(c))
            except Exception as exc:  # noqa: BLE001
                rows.append(
                    {"case": c["name"], "ok": False,
                     "error": f"{type(exc).__name__}: {exc}"[:200]}
                )
    for r in rows:
        r["device"] = device
        r["mode"] = mode
    header = f"device: {device}  backend: {backend}  mode: {mode}"
    print(header)

    def _table(rs):
        cols = ["case", "fwd", "dq", "dk", "dv", "tok", "device", "ok"]
        lines = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
        for r in rs:
            dev = f"{r.get('device', '?')} ({r.get('mode', '?')})"
            if "error" in r:
                lines.append(
                    f"| {r['case']} | error: {r['error']} |" + " |" * 4
                    + f" {dev} | FAIL |"
                )
                continue
            lines.append(
                f"| {r['case']} | {r['fwd_max_err']} "
                f"| {r.get('dq_max_err', '-')} | {r.get('dk_max_err', '-')} "
                f"| {r.get('dv_max_err', '-')} | {r.get('tok_match', '-')} "
                f"| {dev} "
                f"| {'PASS' if r['ok'] else 'FAIL'} |"
            )
        return "\n".join(lines)

    print(_table(rows))
    all_ok = bool(rows) and all(r["ok"] for r in rows)
    payload = {
        "rows": rows, "device": device, "backend": backend, "all_ok": all_ok,
    }
    print(json.dumps(payload))
    if args.write_docs:
        root = os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", "..", "docs", "benchmarks")
        )
        json_path = os.path.join(root, "attention_parity.json")
        old = None
        try:
            with open(json_path) as f:
                old = json.load(f)
        except (OSError, ValueError):
            pass
        rows = merge_rows(rows, old)
        # The RECORD's verdict (merged rows) is the exit code under
        # --write-docs: an interpreter rerun whose cpu rows lose to a
        # committed Mosaic row must not fail a healthy record.
        all_ok = bool(rows) and all(r["ok"] for r in rows)
        payload = {
            "rows": rows,
            "device": device,
            "backend": backend,
            "all_ok": all_ok,
        }
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=1)
        with open(os.path.join(root, "attention_parity.md"), "w") as f:
            f.write(
                "# Flash-attention parity record (Mosaic vs dense XLA)\n\n"
                "Generated by `python -m distributed_tensorflow_tpu.tools."
                f"attention_parity --write-docs` — last run {header}.\n"
                "Per-row `device` is the backend that actually ran the "
                "row (merge rule: an\ninterpreter rerun never overwrites "
                "a Mosaic row — kernel PROOFS are the\nMosaic-tagged rows "
                "only; interpreter rows are correctness previews awaiting"
                "\nthe chip rerun). Forward and q/k/v gradient max-abs "
                "errors vs the dense\noracle, bf16 inputs, per feature "
                "(causal/window/banding/GQA/kv_lens/offset).\n"
                "`decode-fused-vs-xla:*` rows (round 18): the per-layer "
                "Pallas decode-step\nkernel (`decode_engine="
                '"pallas-layer"`) vs the unrolled XLA decode engine —\n'
                "max logit error over an 8-step greedy decode plus the "
                "token-agreement\nfraction (`tok`). "
                "`decode-mega-vs-xla:*` rows (round 20): the multi-layer"
                "\nmegakernel (`decode_engine=\"pallas\"`, one launch per "
                "token, in-kernel\naliased cache commit) over the same "
                "matrix. `verify-fused-vs-xla:*` rows\n(round 20): the "
                "fused speculation-verify kernel vs the XLA extend path "
                "—\nlogit/argmax parity on valid suffix rows plus the "
                "bitwise cache-commit\ncheck (`ok` includes it).\n\n"
                + _table(rows) + "\n"
            )
        print(f"wrote {root}/attention_parity.md")
    return 0 if all_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
