"""On-chip flash-attention parity record: Mosaic kernels vs dense XLA.

The fast test suite proves the Pallas kernels against the dense oracle in
*interpreter* mode (conftest forces CPU); the Mosaic-compiled path on the
real chip was verified interactively in round 2 but recorded only as a
commit-message claim (VERDICT round-2 weak #7). This tool makes that
verification a regenerable artifact: it runs forward AND gradient parity
for the full feature matrix — causal, sliding window (both sides of the
banding crossover), GQA, key-padding (kv_lens), and the ring-composition
``offset`` — against ``dense_attention`` on whatever backend it's launched
on, and emits one JSON line with per-case max errors and pass/fail.
Round 13 adds a ``fused-vs-split:*`` row per case: the one-pass fused
dq+dk+dv backward (the new default) against the two-kernel split on the
same forward, so the on-chip record covers the fused kernel explicitly.
Round 18 adds ``decode-fused-vs-xla:*`` rows: the fused Pallas
decode-step kernel (ops/pallas_decode.py, ``decode_engine="pallas"``)
against the unrolled XLA decode engine over a short greedy decode —
max logit error across steps plus the greedy-token agreement fraction,
per serving-config feature (dense / GQA / rolling window / paged /
int8 / fp8 KV). The round-3 lesson applies to these too: the CPU
interpreter tolerates Mosaic-only bugs, so the rows only count as a
kernel proof when the header says Mosaic.

Usage (on the TPU)::

    python -m distributed_tensorflow_tpu.tools.attention_parity \
        --write-docs      # regenerates docs/benchmarks/attention_parity.md

Tolerances are bf16-scale (the kernels do f32 softmax math over bf16 MXU
dots, like XLA's default) — rtol 2e-2 / atol 2e-2 on values whose scale
is O(1); gradients compare at the same bar.
"""

from __future__ import annotations

import argparse
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

RTOL, ATOL = 2e-2, 2e-2


def _case(name, *, l=512, h=4, hkv=None, d=64, causal=True, window=None,
          kv_lens=None, offset=0, block=None):
    return dict(
        name=name, l=l, h=h, hkv=hkv or h, d=d, causal=causal, window=window,
        kv_lens=kv_lens, offset=offset, block=block,
    )


CASES = [
    _case("causal"),
    _case("noncausal", causal=False),
    _case("causal-block128", block=128),
    _case("window-below-banding", window=256, l=512),  # 4W > L: banding off
    _case("window-banded", window=64, l=1024),  # 4W <= L: banded index maps
    _case("gqa", h=8, hkv=2),
    _case("gqa-window", h=8, hkv=2, window=128, l=1024),
    _case("kv-lens", kv_lens=(301, 444)),
    _case("kv-lens-gqa", h=8, hkv=2, kv_lens=(301, 444)),
    _case("offset-shifted-band", window=96, offset=256, l=512),
]


def _decode_case(name, *, kv_dtype="bf16", heads=4, kv_heads=None,
                 window=None, paged=False):
    return dict(
        name=name, kv_dtype=kv_dtype, heads=heads,
        kv_heads=kv_heads or heads, window=window, paged=paged,
    )


DECODE_CASES = [
    _decode_case("decode-fused-vs-xla:dense-bf16"),
    _decode_case("decode-fused-vs-xla:dense-int8", kv_dtype="int8"),
    _decode_case("decode-fused-vs-xla:dense-fp8", kv_dtype="fp8"),
    _decode_case("decode-fused-vs-xla:gqa", heads=8, kv_heads=2),
    _decode_case("decode-fused-vs-xla:window-rolling", window=16),
    _decode_case(
        "decode-fused-vs-xla:paged-int8", kv_dtype="int8", paged=True
    ),
]


def run_decode_case(c: dict) -> dict:
    """One serving config's fused-vs-XLA decode parity: prefill three
    ragged prompts into slots, then 8 greedy decode steps with BOTH
    engines fed the XLA engine's token stream (teacher-forced) — so
    every step scores the same prefix and the max logit error stays a
    kernel-parity measurement even after a budgeted argmax flip (self-
    fed streams would diverge at the first flip and the error metric
    would measure different prefixes, not the kernel). Token agreement
    is the per-step argmax match under those identical prefixes; ``ok``
    needs logit error under the shared tolerance bar and ≥ 90% token
    agreement (bf16 compute — flips at near-ties are the budgeted
    residual; tests/test_pallas_decode.py pins the tight f32
    contract)."""
    import numpy as np

    from distributed_tensorflow_tpu.models.gpt import GPTLM

    m = GPTLM(
        vocab_size=97, max_len=64, model_dim=32, num_heads=c["heads"],
        num_kv_heads=c["kv_heads"], num_layers=2, pos_embedding="rope",
        window=c["window"],
    )
    params = m.init(seed=1)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 97, (3, 8)), jnp.int32)
    lens = jnp.asarray([8, 5, 3], jnp.int32)
    admit = jnp.ones((3,), bool)
    if c["paged"]:
        cache = m.empty_paged_cache(3, 24, block_size=8, kv_dtype=c["kv_dtype"])
        tables = np.zeros((3, m.paged_blocks_per_slot(8)), np.int32)
        nb = m.paged_blocks_per_slot(8)
        for s in range(3):
            tables[s] = np.arange(1 + s * nb, 1 + (s + 1) * nb) % 24
        cache = cache._replace(block_tables=jnp.asarray(tables))
        _, cache = m.extend_paged(
            params, cache, toks, lens, jnp.zeros((3,), jnp.int32), admit
        )
        cache = cache._replace(lengths=lens)
        decode = m.decode_paged
    else:
        cache = m.empty_slot_cache(3, c["kv_dtype"])
        _, cache = m.prefill_slots(params, cache, toks, lens, admit)
        decode = m.decode_slots
    tok = jnp.asarray([1, 2, 3], jnp.int32)
    cx = cp = cache
    tx = tok
    steps, agree, err = 8, 0, 0.0
    for _ in range(steps):
        lx, cx = decode(params, tx, cx, engine="xla")
        lp, cp = decode(params, tx, cp, engine="pallas")
        err = max(err, float(jnp.max(jnp.abs(
            lx.astype(jnp.float32) - lp.astype(jnp.float32)
        ))))
        nx = jnp.argmax(lx, -1).astype(jnp.int32)
        npal = jnp.argmax(lp, -1).astype(jnp.int32)
        agree += int((np.asarray(nx) == np.asarray(npal)).sum())
        tx = nx  # teacher-force the XLA stream into BOTH engines
    tok_match = agree / (steps * 3)
    tol = ATOL + RTOL
    return {
        "case": c["name"],
        "fwd_max_err": round(err, 5),
        "tok_match": round(tok_match, 4),
        "ok": bool(err < tol and tok_match >= 0.9),
    }


def run_case(c: dict) -> dict:
    from distributed_tensorflow_tpu.ops.pallas_attention import flash_attention
    from distributed_tensorflow_tpu.ops.ring_attention import dense_attention

    b = 2
    kq, kk, kv, kc = jax.random.split(jax.random.key(7), 4)
    q = jax.random.normal(kq, (b, c["l"], c["h"], c["d"]), jnp.bfloat16)
    k = jax.random.normal(kk, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    v = jax.random.normal(kv, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    lens = (
        None if c["kv_lens"] is None else jnp.asarray(c["kv_lens"], jnp.int32)
    )
    kw = dict(
        causal=c["causal"], window=c["window"], kv_lens=lens,
        block_q=c["block"], block_k=c["block"],
    )
    cot = jax.random.normal(kc, q.shape, jnp.float32)

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, offset=c["offset"], **kw)

    def dense_fn(q, k, v):
        # dense_attention has no offset — emulate the shifted band by
        # masking scores directly (the definition offset implements).
        if c["offset"]:
            qf = q.astype(jnp.float32)
            kf = k.astype(jnp.float32)
            kf, vf = kf, v.astype(jnp.float32)
            from distributed_tensorflow_tpu.ops.ring_attention import repeat_kv

            kf, vf = repeat_kv(kf, vf, q.shape[2])
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(c["d"])
            diff = (
                jnp.arange(c["l"])[:, None] + c["offset"]
                - jnp.arange(c["l"])[None, :]
            )
            mask = diff >= 0
            if c["window"] is not None:
                mask &= diff < c["window"]
            s = jnp.where(mask[None, None], s, -1e30)
            w = jax.nn.softmax(s, axis=-1)
            # Fully-masked rows (offset pushes the whole band past the
            # sequence end): match the kernel's zero-output convention
            # instead of softmax-of-constants garbage, so outputs AND
            # gradients are comparable everywhere.
            row_valid = mask.any(axis=-1)[None, None, :, None]
            w = jnp.where(row_valid, w, 0.0)
            out = jnp.einsum("bhqk,bkhd->bqhd", w, vf)
            return out.astype(q.dtype)
        return dense_attention(
            q, k, v, causal=c["causal"], window=c["window"], kv_lens=lens
        )

    f_out = jax.jit(flash_fn)(q, k, v)
    d_out = jax.jit(dense_fn)(q, k, v)

    def gsum(fn):
        return jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) * cot),
                argnums=(0, 1, 2),
            )
        )(q, k, v)

    g_f, g_d = gsum(flash_fn), gsum(dense_fn)

    # Compare only rows that are not fully masked (padded queries whose
    # whole window lies beyond kv_len are documented garbage on both
    # sides, with different conventions).
    def err(a, b):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        return float(np.max(np.abs(a - b)))

    fwd_err = err(f_out, d_out)
    grad_errs = {n: err(a, b) for n, a, b in zip("qkv", g_f, g_d)}
    tol = ATOL + RTOL  # values are O(1)
    ok = fwd_err < tol and all(e < tol for e in grad_errs.values())
    return {
        "case": c["name"],
        "fwd_max_err": round(fwd_err, 5),
        "dq_max_err": round(grad_errs["q"], 5),
        "dk_max_err": round(grad_errs["k"], 5),
        "dv_max_err": round(grad_errs["v"], 5),
        "ok": bool(ok),
    }


def run_fused_split_case(c: dict) -> dict:
    """Round-13 rows: the fused one-pass backward against the two-kernel
    split on the SAME flash forward — the on-chip record for the new
    kernel (the main rows already run the fused default against dense;
    this isolates fused-vs-split, which should be ~bitwise since both
    accumulate in f32). The round-3 lesson applies verbatim: the CPU
    interpreter tolerates Mosaic-only bugs, so these rows only count
    when the header says Mosaic."""
    from distributed_tensorflow_tpu.ops.pallas_attention import flash_attention

    b = 2
    kq, kk, kv, kc = jax.random.split(jax.random.key(7), 4)
    q = jax.random.normal(kq, (b, c["l"], c["h"], c["d"]), jnp.bfloat16)
    k = jax.random.normal(kk, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    v = jax.random.normal(kv, (b, c["l"], c["hkv"], c["d"]), jnp.bfloat16)
    lens = (
        None if c["kv_lens"] is None else jnp.asarray(c["kv_lens"], jnp.int32)
    )
    cot = jax.random.normal(kc, q.shape, jnp.float32)
    kw = dict(
        causal=c["causal"], window=c["window"], kv_lens=lens,
        offset=c["offset"], block_q=c["block"], block_k=c["block"],
    )

    def gsum(fused):
        return jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, fused=fused, **kw).astype(
                        jnp.float32
                    )
                    * cot
                ),
                argnums=(0, 1, 2),
            )
        )(q, k, v)

    g_f, g_s = gsum(True), gsum(False)

    def err(a, b):
        return float(np.max(np.abs(np.asarray(a, np.float32) - np.asarray(b, np.float32))))

    grad_errs = {n: err(a, b) for n, a, b in zip("qkv", g_f, g_s)}
    tol = ATOL + RTOL
    ok = all(e < tol for e in grad_errs.values())
    return {
        "case": f"fused-vs-split:{c['name']}",
        "fwd_max_err": 0.0,  # same forward kernel by construction
        "dq_max_err": round(grad_errs["q"], 5),
        "dk_max_err": round(grad_errs["k"], 5),
        "dv_max_err": round(grad_errs["v"], 5),
        "ok": bool(ok),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--write-docs", action="store_true")
    ap.add_argument("--cases", nargs="+", default=None)
    args = ap.parse_args(argv)
    known = {c["name"] for c in CASES} | {c["name"] for c in DECODE_CASES}
    if args.cases:
        unknown = set(args.cases) - known
        if unknown:
            # A typo must not yield a vacuously-green (empty) record.
            ap.error(
                f"unknown case(s) {sorted(unknown)}; known: {sorted(known)}"
            )
    rows = []
    for c in CASES:
        if args.cases and c["name"] not in args.cases:
            continue
        for runner, label in ((run_case, c["name"]),
                              (run_fused_split_case, f"fused-vs-split:{c['name']}")):
            try:
                rows.append(runner(c))
            except Exception as exc:  # noqa: BLE001
                rows.append(
                    {"case": label, "ok": False,
                     "error": f"{type(exc).__name__}: {exc}"[:200]}
                )
    for c in DECODE_CASES:
        if args.cases and c["name"] not in args.cases:
            continue
        try:
            rows.append(run_decode_case(c))
        except Exception as exc:  # noqa: BLE001
            rows.append(
                {"case": c["name"], "ok": False,
                 "error": f"{type(exc).__name__}: {exc}"[:200]}
            )
    device = jax.devices()[0].device_kind
    backend = jax.default_backend()
    all_ok = bool(rows) and all(r["ok"] for r in rows)
    header = (
        f"device: {device}  backend: {backend}  "
        f"mode: {'Mosaic' if backend == 'tpu' else 'interpreter'}"
    )
    print(header)
    cols = ["case", "fwd", "dq", "dk", "dv", "tok", "ok"]
    lines = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if "error" in r:
            lines.append(f"| {r['case']} | error: {r['error']} |" + " |" * 5)
            continue
        lines.append(
            f"| {r['case']} | {r['fwd_max_err']} "
            f"| {r.get('dq_max_err', '-')} | {r.get('dk_max_err', '-')} "
            f"| {r.get('dv_max_err', '-')} | {r.get('tok_match', '-')} "
            f"| {'PASS' if r['ok'] else 'FAIL'} |"
        )
    table = "\n".join(lines)
    print(table)
    payload = {
        "rows": rows, "device": device, "backend": backend, "all_ok": all_ok,
    }
    print(json.dumps(payload))
    if args.write_docs:
        root = os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", "..", "docs", "benchmarks")
        )
        with open(os.path.join(root, "attention_parity.json"), "w") as f:
            json.dump(payload, f, indent=1)
        with open(os.path.join(root, "attention_parity.md"), "w") as f:
            f.write(
                "# Flash-attention parity record (Mosaic vs dense XLA)\n\n"
                "Generated by `python -m distributed_tensorflow_tpu.tools."
                f"attention_parity --write-docs` — {header}. Forward and\n"
                "q/k/v gradient max-abs errors vs the dense oracle, bf16\n"
                "inputs, per feature (causal/window/banding/GQA/kv_lens/"
                "offset).\n`decode-fused-vs-xla:*` rows (round 18): the "
                "fused Pallas decode-step\nkernel vs the unrolled XLA "
                "decode engine — max logit error over an\n8-step greedy "
                "decode plus the token-agreement fraction (`tok`).\n\n"
                + table + "\n"
            )
        print(f"wrote {root}/attention_parity.md")
    return 0 if all_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
