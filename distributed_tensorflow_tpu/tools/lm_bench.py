"""On-chip LM training benchmark: throughput (tokens/sec) + MFU per config.

The reference's method was measure-everything-and-publish — every mode has
an s/epoch number in its experiment log (reference README.md:13-15,38-40).
Round 2 built the whole GPT surface and measured none of it (VERDICT
round-2 missing #1); this tool closes that: it times `make_lm_train_step`
on the real chip with the only two disciplines that give truthful numbers
here (CLAUDE.md):

- ``steps`` train steps amortized inside ONE compiled dispatch (a
  ``lax.scan`` whose carry is the optimizer state — each step depends on
  the previous params, so nothing hoists), resolving per-step time far
  below the ~12 ms tunnel dispatch floor;
- a D2H value fetch (the final step's loss) as the execution barrier.

MFU = compiled-FLOPs-per-step (XLA's own cost model, via
``tools/cost_analysis.analyze_lm`` — the same program, not a hand
formula) / measured step time / chip peak FLOPs.

Usage::

    python -m distributed_tensorflow_tpu.tools.lm_bench            # full grid
    python -m distributed_tensorflow_tpu.tools.lm_bench --steps 16 \
        --configs gpt-s-L512-xla gpt-s-L512-flash

Prints a markdown table and a one-line JSON summary;
``docs/benchmarks/lm_tpu.md`` + ``lm_tpu.json`` are regenerated from this
tool's output (``--write-docs``).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import optax
from jax import lax

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.tools.cost_analysis import _chip_peaks, analyze_lm

# Each entry: model kwargs + batch. Two (L, d, layers) points, and at the
# long-L point the attention-variant axis (xla / flash / flash+window /
# GQA) the round-2 verdict asked to separate.
# Batch sizes chosen to FILL the chip (MFU collapses when per-step matmuls
# are too small to tile the MXU — B=2 toy batches measured 1-2% MFU).
CONFIGS = {
    # short-context point: d=256, 4 layers, L=512
    "gpt-s-L512-xla": dict(
        batch=32,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=512),
    ),
    "gpt-s-L512-flash": dict(
        batch=32,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=512,
            attention_impl="flash", flash_min_len=0,
        ),
    ),
    # long-context point: same model at L=2048
    "gpt-s-L2048-xla": dict(
        batch=8,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=2048),
    ),
    "gpt-s-L2048-flash": dict(
        batch=8,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=2048,
            attention_impl="flash", flash_min_len=0,
        ),
    ),
    "gpt-s-L2048-flash-W512": dict(
        batch=8,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=2048,
            attention_impl="flash", flash_min_len=0, window=512,
        ),
    ),
    "gpt-s-L2048-flash-gqa2": dict(
        batch=8,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, num_kv_heads=2,
            max_len=2048, attention_impl="flash", flash_min_len=0,
        ),
    ),
    # bigger-model points: d=512 and d=1024 (wider matmuls → real MFU)
    "gpt-m-L1024-flash": dict(
        batch=16,
        model=dict(
            model_dim=512, num_layers=8, num_heads=8, max_len=1024,
            attention_impl="flash", flash_min_len=0,
        ),
    ),
    "gpt-l-L1024-flash": dict(
        batch=8,
        model=dict(
            model_dim=1024, num_layers=8, num_heads=16, max_len=1024,
            attention_impl="flash", flash_min_len=0,
        ),
    ),
    # MXU-sized points (round 5): d=2048 tiles the 128-lane MXU properly;
    # remat=True is required to fit HBM (the unremat'd d=2048/L=2048
    # stash is ~20 GB) and trades recompute the model-FLOPs MFU† column
    # deliberately does not credit. These rows are the measured proof
    # that the toy rows' low MFU was the workload (docs/benchmarks/
    # lm_phases.md has the per-phase breakdown).
    "gpt-xl-L1024-flash-remat": dict(
        batch=16,
        model=dict(
            model_dim=2048, num_layers=4, num_heads=16, max_len=1024,
            attention_impl="flash", remat=True,
        ),
    ),
    "gpt-xl-L2048-flash-remat": dict(
        batch=8,
        model=dict(
            model_dim=2048, num_layers=4, num_heads=16, max_len=2048,
            attention_impl="flash", remat=True,
        ),
    ),
}
_VOCAB = 8192

# Generation (KV-cache decode) configs: one scan-compiled greedy_decode
# dispatch per timing — prefill 256 prompt tokens, decode 256 more. The
# variant axis: full-length cache vs rolling windowed cache (O(W) slots)
# vs GQA (cache at Hkv width, grouped-einsum attend — no repeat).
DECODE_CONFIGS = {
    "decode-full": dict(
        batch=8, prompt=256, max_new=256,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=1024),
    ),
    "decode-window256": dict(
        batch=8, prompt=256, max_new=256,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=1024,
            window=256,
        ),
    ),
    "decode-gqa2": dict(
        batch=8, prompt=256, max_new=256,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, num_kv_heads=2,
            max_len=1024,
        ),
    ),
    "decode-long-full": dict(
        batch=4, prompt=256, max_new=256,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=4096),
    ),
    "decode-long-window256": dict(
        batch=4, prompt=256, max_new=256,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=4096,
            window=256,
        ),
    ),
}


def bench_decode(name: str, *, seed: int = 0) -> dict:
    spec = DECODE_CONFIGS[name]
    model = GPTLM(vocab_size=_VOCAB, **spec["model"])
    b, p_len, max_new = spec["batch"], spec["prompt"], spec["max_new"]
    params = model.init(seed=1)
    prompt = jax.random.randint(
        jax.random.key(seed), (b, p_len), 0, _VOCAB, jnp.int32
    )
    # Two-point (utils/sync.two_point_seconds): difference a max_new-token
    # and a short-token decode — cancels the tunnel roundtrip AND the
    # shared prefill, leaving pure per-token decode cost. Fast decodes
    # (windowed, GQA) run tens of µs/token, so one generation's delta sits
    # BELOW the ~±10 ms dispatch jitter (a committed record briefly showed
    # a 13x phantom speedup from exactly this); chain `reps_in` full
    # generations per dispatch — each rep's prompt is the previous rep's
    # tail, a genuine dependency XLA cannot CSE — so the differenced span
    # is reps_in·(max_new−short) tokens.
    from distributed_tensorflow_tpu.utils.sync import (
        timed_fetch,
        two_point_seconds,
    )

    short = max_new // 4
    reps_in = 8

    def make_chain(new_tokens):
        @jax.jit
        def chain(pr):
            def body(pr, _):
                out = model.greedy_decode(params, pr, new_tokens)
                return out[:, -p_len:].astype(pr.dtype), None

            pr, _ = lax.scan(body, pr, None, length=reps_in)
            return pr

        return chain

    gen1, gen4 = make_chain(short), make_chain(max_new)

    def timed(fn):
        return lambda: timed_fetch(fn, prompt)[0]

    timed(gen1)(), timed(gen4)()  # compile both
    sec_per_tok = two_point_seconds(
        timed(gen1), timed(gen4), reps_in * (max_new - short), reps=3
    )
    return {
        "config": name,
        "batch": b,
        "prompt": p_len,
        "max_new": max_new,
        "cache_len": model.cache_len,
        "ms_per_token": round(sec_per_tok * 1e3, 3),
        "gen_tokens_per_sec": round(b / sec_per_tok, 1),
    }


def render_decode(rows) -> str:
    cols = ["config", "B", "prompt", "new", "cache", "ms/token", "gen tok/s"]
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if "error" in r:
            out.append(f"| {r['config']} | error: {r['error']} |" + " |" * 5)
            continue
        out.append(
            "| {config} | {batch} | {prompt} | {max_new} | {cache_len} | "
            "{ms_per_token:.2f} | {gen_tokens_per_sec:,.0f} |".format(**r)
        )
    return "\n".join(out)


def bench_config(
    name: str, *, steps: int = 32, lr: float = 1e-3, seed: int = 0,
    ceiling_tflops: float | None = None, model_overrides: dict | None = None,
) -> dict:
    spec = CONFIGS[name]
    # Ad-hoc A/B knobs (round 13: remat="selective", matmul_dtype=...)
    # land on every selected config; main() refuses them with
    # --write-docs so a probe cannot re-anchor the committed record.
    mkw = dict(spec["model"], **(model_overrides or {}))
    model = GPTLM(vocab_size=_VOCAB, **mkw)
    b, l = spec["batch"], model.max_len
    params = model.init(seed=1)
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.key(seed), (b, l), 0, _VOCAB, jnp.int32
    )

    def make_epoch(length):
        @jax.jit
        def epoch(params, opt_state, tokens):
            def body(carry, _):
                params, opt_state = carry
                loss, grads = jax.value_and_grad(model.loss)(params, tokens)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = lax.scan(
                body, (params, opt_state), None, length=length
            )
            return params, opt_state, losses

        return epoch

    # TWO-POINT timing (tools/roofline_bench.py rationale): one
    # dispatch+fetch through the tunnel carries a ~100 ms fixed roundtrip;
    # dividing a single chain's wall time by `steps` folds that roundtrip
    # into every step (the round-3 numbers did exactly this — at 5-50 ms
    # true step times it inflated them by 10-100%, which is what the
    # "effective ceiling" story was built on). Difference a 4k-step and a
    # k-step warm dispatch instead; median over reps vs tunnel jitter.
    e1, e4 = make_epoch(steps), make_epoch(4 * steps)

    from distributed_tensorflow_tpu.utils.sync import (
        timed_fetch,
        two_point_seconds,
    )

    last = {}

    def timed(fn):
        def run():
            dt, out = timed_fetch(fn, params, opt_state, tokens)
            last[fn] = float(out[2][-1])  # after the barrier: losses[-1]
            return dt

        return run

    timed(e1)(), timed(e4)()  # compile + warm (fetch = barrier)
    sec_per_step = two_point_seconds(
        timed(e1), timed(e4), 3 * steps, reps=3
    )
    # The loss after exactly `steps` steps (e1's chain) — the field's
    # meaning must track steps_per_dispatch, not the 4x timing chain.
    final_loss = last[e1]
    dt = sec_per_step * steps

    step_ms = sec_per_step * 1e3
    tokens_per_sec = b * l * steps / dt
    row = {
        "config": name,
        "batch": b,
        "seq_len": l,
        "steps_per_dispatch": steps,
        # Measurement provenance — carried-forward rows in a chunked
        # regeneration keep their own method/steps (see --write-docs).
        "timing": f"two-point d({4 * steps}-{steps})x3",
        "step_ms": round(step_ms, 3),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "final_loss": round(final_loss, 4),
    }
    # MFU from the XLA cost model of the SAME single step program.
    report = analyze_lm(model, batch_size=b, optimizer=opt)
    row["flops_per_step"] = report["flops_per_step"]
    row["param_count"] = report["param_count"]
    # Model FLOPs (the scaling-book 6·N·P convention): what the MODEL
    # mathematically requires per step — counts remat recompute as zero
    # and undercounts attention, so MFU† is the conservative utilization
    # the field quotes; the XLA-counted column reflects the compiled
    # program's own op count. N EXCLUDES the embedding and position
    # tables (the Kaplan/Chinchilla reading: lookups and adds do not pay
    # the per-token 2N matmul FLOPs the 6N derivation counts; the tied
    # LM head shares the embedding table). Round 5 used total params,
    # which at vocab 8192/d=256 inflated MFU† by the table's 39% share
    # (ADVICE round 5); lm_tpu.json keeps both counts.
    row["param_count_nonembed"] = report["param_count"] - int(
        params.embed.size + params.pos.size
    )
    row["model_flops_per_step"] = 6 * row["param_count_nonembed"] * b * l
    peaks = _chip_peaks(jax.devices()[0])
    if peaks and report["flops_per_step"]:
        achieved = report["flops_per_step"] / sec_per_step
        row["mfu_pct"] = round(100 * achieved / peaks["flops"], 2)
        # MFU* — against the MEASURED bf16 ceiling (tools/roofline_bench),
        # not the spec sheet: 100% means the step saturates what this
        # chip+tunnel actually sustains on pure matmul chains.
        if ceiling_tflops:
            row["mfu_star_pct"] = round(
                100 * achieved / (ceiling_tflops * 1e12), 2
            )
            row["mfu_model_pct"] = round(
                100
                * row["model_flops_per_step"]
                / sec_per_step
                / (ceiling_tflops * 1e12),
                2,
            )
        else:
            row["mfu_star_pct"] = None
            row["mfu_model_pct"] = None
    else:
        row["mfu_pct"] = None
        row["mfu_star_pct"] = None
        row["mfu_model_pct"] = None
    return row


def _roofline_ceiling() -> float | None:
    """Measured bf16 ceiling from the committed roofline record, if any
    (shared: tools/cost_analysis.measured_ceiling_tflops)."""
    from distributed_tensorflow_tpu.tools.cost_analysis import (
        measured_ceiling_tflops,
    )

    return measured_ceiling_tflops()


def merge_rows(new, old, order):
    """Carry-forward merge for chunked --write-docs regeneration (shared
    with tools/lm_phase_bench): keep previously committed good rows for
    configs not re-measured this run; an error row never displaces a
    previously good measurement."""
    old_good = {r["config"]: r for r in old if "error" not in r}
    new_good = {r["config"] for r in new if "error" not in r}
    out = [
        r for r in new if "error" not in r or r["config"] not in old_good
    ] + [r for c, r in old_good.items() if c not in new_good]
    out.sort(
        key=lambda r: order.index(r["config"])
        if r.get("config") in order
        else len(order)
    )
    return out


def _nonembed_param_count(row) -> int | None:
    """Non-embedding N for a committed row (offline migration of records
    written before round 6): total params minus the d·(vocab + max_len)
    embedding+position tables, derived from the config's model spec."""
    spec = CONFIGS.get(row.get("config"))
    if spec is None or not row.get("param_count"):
        return None
    d = spec["model"]["model_dim"]
    return row["param_count"] - d * (_VOCAB + spec["model"]["max_len"])


def refresh_derived(rows, ceiling, peaks=None) -> None:
    """Recompute every derived column of committed/carried rows from
    their MEASURED fields (step_ms, flops_per_step, param_count): the
    non-embedding N and 6N model FLOPs (round-6 MFU† convention), MFU*
    against the CURRENT ceiling, and — when chip peaks are known — the
    spec-peak MFU. Keeps a chunked regeneration from silently mixing
    denominators, and lets ``--recompute-docs`` migrate the record
    off-chip (no re-measurement)."""
    for r in rows:
        if "error" in r or not r.get("flops_per_step"):
            continue
        achieved = r["flops_per_step"] / (r["step_ms"] / 1e3)
        if "param_count_nonembed" not in r:
            ne = _nonembed_param_count(r)
            if ne is not None:
                r["param_count_nonembed"] = ne
        n_eff = r.get("param_count_nonembed") or r.get("param_count")
        if n_eff:
            r["model_flops_per_step"] = 6 * n_eff * r["batch"] * r["seq_len"]
        if ceiling:
            r["mfu_star_pct"] = round(100 * achieved / (ceiling * 1e12), 2)
            if r.get("model_flops_per_step"):
                r["mfu_model_pct"] = round(
                    100
                    * r["model_flops_per_step"]
                    / (r["step_ms"] / 1e3)
                    / (ceiling * 1e12),
                    2,
                )
        if peaks and peaks.get("flops"):
            r["mfu_pct"] = round(100 * achieved / peaks["flops"], 2)


def run(
    configs=None, *, steps: int = 32, ceiling_tflops=None,
    model_overrides: dict | None = None,
) -> list[dict]:
    rows = []
    for name in configs or CONFIGS:
        try:
            rows.append(
                bench_config(
                    name, steps=steps, ceiling_tflops=ceiling_tflops,
                    model_overrides=model_overrides,
                )
            )
        except Exception as exc:  # noqa: BLE001 — record, keep sweeping
            rows.append(
                {"config": name, "error": f"{type(exc).__name__}: {exc}"[:200]}
            )
    return rows


def render(rows) -> str:
    cols = [
        "config", "B", "L", "step (ms)", "tokens/s", "MFU %", "MFU* %",
        "MFU† %", "params",
    ]
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if "error" in r:
            out.append(f"| {r['config']} | error: {r['error']} |" + " |" * 7)
            continue
        fmt = lambda v: ("%.1f" % v) if v is not None else "—"  # noqa: E731
        out.append(
            "| {config} | {batch} | {seq_len} | {step_ms:.2f} | "
            "{tokens_per_sec:,.0f} | {mfu} | {mfu_star} | {mfu_model} | "
            "{param_count:,} |".format(
                mfu=fmt(r["mfu_pct"]),
                mfu_star=fmt(r.get("mfu_star_pct")),
                mfu_model=fmt(r.get("mfu_model_pct")),
                **r,
            )
        )
    return "\n".join(out)


def emit_bench_events(rows, device: str, events_path: str) -> list[dict]:
    """The measured LM rows as ``bench_point`` journal events (round 10):
    one event per config carrying tokens/s and the MFU columns, so the
    docs tables and the journal share one source
    (``tools/perf_record.py --journal`` reads them back)."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    j = EventJournal(events_path, run_id="lm_bench")
    try:
        out = []
        for r in rows:
            if "error" in r or "tokens_per_sec" not in r:
                continue
            out.append(
                j.emit(
                    "bench_point",
                    tool="lm_bench",
                    name=r["config"],
                    value=r["tokens_per_sec"],
                    unit="tokens/s",
                    device=device,
                    step_ms=r.get("step_ms"),
                    mfu_model_pct=r.get("mfu_model_pct"),
                    mfu_star_pct=r.get("mfu_star_pct"),
                )
            )
        return out
    finally:
        j.close()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--configs", nargs="+", default=None, choices=sorted(CONFIGS))
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument(
        "--write-docs",
        action="store_true",
        help="regenerate docs/benchmarks/lm_tpu.{md,json}",
    )
    ap.add_argument(
        "--decode",
        action="store_true",
        help="also run the KV-cache generation configs",
    )
    ap.add_argument(
        "--ceiling-tflops",
        type=float,
        default=None,
        help="measured bf16 ceiling for the MFU* column (default: read "
        "docs/benchmarks/roofline_tpu.json)",
    )
    ap.add_argument(
        "--recompute-docs",
        action="store_true",
        help="no measurement: reload docs/benchmarks/lm_tpu.json and "
        "recompute every derived column (non-embedding 6N model FLOPs, "
        "MFU*/MFU† vs the current ceiling) from the committed measured "
        "fields, then rewrite md+json — runs anywhere, no chip needed",
    )
    ap.add_argument(
        "--events",
        default=None,
        help="append the measured rows as bench_point journal events "
        "(default with --write-docs: docs/benchmarks/events.jsonl)",
    )
    ap.add_argument(
        "--remat",
        choices=("plain", "selective"),
        default=None,
        help="override every selected config's remat mode (A/B the "
        "round-13 selective policy at the committed shapes); refused "
        "with --write-docs",
    )
    ap.add_argument(
        "--matmul-dtype",
        choices=("int8", "fp8"),
        default=None,
        help="run with quantized projection matmuls (GPTLM "
        "matmul_dtype); refused with --write-docs",
    )
    args = ap.parse_args(argv)
    if (args.remat or args.matmul_dtype) and (args.write_docs or args.events):
        # Probes must touch neither the committed docs nor the gate's
        # bench_point series (their keys carry no override tag — probe
        # points would contaminate the default-config band).
        ap.error(
            "--remat/--matmul-dtype are ad-hoc probes; the committed "
            "record and the gate's event series track the configs as "
            "written (drop --write-docs/--events)"
        )
    ceiling = args.ceiling_tflops or _roofline_ceiling()
    root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..", "docs", "benchmarks")
    )
    json_path = os.path.join(root, "lm_tpu.json")
    if args.recompute_docs:
        with open(json_path) as f:
            payload = json.load(f)
        refresh_derived(payload["rows"], ceiling)
        table = render(payload["rows"])
        print(table)
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=1)
        _write_md(
            root,
            table,
            payload.get("decode_rows", []),
            ceiling,
            payload.get("device", "TPU v5 lite"),
            "--recompute-docs",
        )
        print(f"recomputed {root}/lm_tpu.md and lm_tpu.json (no re-measurement)")
        return
    overrides = {}
    if args.remat:
        overrides["remat"] = True if args.remat == "plain" else "selective"
    if args.matmul_dtype:
        overrides["matmul_dtype"] = args.matmul_dtype
    rows = run(
        args.configs, steps=args.steps, ceiling_tflops=ceiling,
        model_overrides=overrides or None,
    )
    # Journal events carry only THIS run's measurements — the carry-
    # forward merge below folds committed rows from other devices/dates
    # into payload["rows"], which must not be re-stamped as fresh points.
    measured_rows = list(rows)
    device = jax.devices()[0].device_kind
    print(
        f"device: {device}  steps/dispatch: {args.steps}  measured "
        f"ceiling: {f'{ceiling} TFLOPS' if ceiling else 'none (run roofline_bench)'}"
    )
    table = render(rows)
    print(table)
    decode_rows = []
    if args.decode:
        for name in DECODE_CONFIGS:
            try:
                decode_rows.append(bench_decode(name))
            except Exception as exc:  # noqa: BLE001
                decode_rows.append(
                    {"config": name,
                     "error": f"{type(exc).__name__}: {exc}"[:200]}
                )
        print(render_decode(decode_rows))
    payload = {
        "rows": rows, "decode_rows": decode_rows, "device": device,
        "backend": jax.default_backend(),
    }
    print(json.dumps(payload))
    if args.write_docs:
        if os.path.exists(json_path):
            # Partial regeneration (a --configs subset, or no --decode)
            # must not erase the rest of the record: carry forward prior
            # rows for configs not re-measured this run. The full sweep
            # exceeds one tunnel session's budget, so the record is
            # routinely rebuilt in chunks. Error rows never displace a
            # previously committed good measurement — a transient tunnel
            # failure during a touch-up run must not erase the record —
            # and an unreadable prior record REFUSES to overwrite (a
            # truncated json from an interrupted write would otherwise
            # silently drop every config not re-measured this run).
            try:
                with open(json_path) as f:
                    prev = json.load(f)
            except Exception as exc:
                print(
                    f"REFUSING to write docs: existing {json_path} is "
                    f"unreadable ({type(exc).__name__}: {exc}) and a "
                    "partial run would erase its other configs; move it "
                    "aside to regenerate from scratch"
                )
                return

            rows = merge_rows(rows, prev.get("rows", []), list(CONFIGS))
            # Carried rows keep their measured times but every derived
            # column tracks the CURRENT conventions (non-embedding 6N,
            # current ceiling) — a roofline re-measure or a denominator
            # fix must not leave the table silently mixing conventions.
            refresh_derived(rows, ceiling, _chip_peaks(jax.devices()[0]) or {})
            payload["rows"] = rows
            table = render(rows)
            decode_rows = merge_rows(
                decode_rows, prev.get("decode_rows", []),
                list(DECODE_CONFIGS),
            )
            payload["decode_rows"] = decode_rows
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=1)
        cmd_flags = f"--steps {args.steps}" + (" --decode" if args.decode else "")
        _write_md(root, table, decode_rows, ceiling, device, cmd_flags)
        print(f"wrote {root}/lm_tpu.md and lm_tpu.json")
    events_path = args.events
    if events_path is None and args.write_docs:
        events_path = os.path.join(root, "events.jsonl")
    if events_path:
        n = len(emit_bench_events(measured_rows, device, events_path))
        print(f"appended {n} bench_point events to {events_path}")


def _write_md(root, table, decode_rows, ceiling, device, cmd_flags) -> None:
    with open(os.path.join(root, "lm_tpu.md"), "w") as f:
        f.write(
            "# LM training on one TPU chip\n\n"
            f"Generated by `python -m distributed_tensorflow_tpu.tools."
            f"lm_bench {cmd_flags} --write-docs` on {device} "
            "(bf16 matmuls, adam, vocab 8192; two-point timing — per "
            "row, step time is the Δ between a 4k- and a k-step warm "
            "dispatch over 3k with D2H-fetch barriers, k and the "
            "method recorded per row in lm_tpu.json `timing` — rows "
            "may come from different chunked runs; MFU = XLA-counted "
            "FLOPs / measured step time / v5e spec peak"
            + (
                ", MFU* = the same against the MEASURED bf16 ceiling "
                f"({ceiling} TFLOPS, docs/benchmarks/roofline_tpu.md), "
                "MFU† = model FLOPs (6·N·tokens, the scaling-book "
                "convention — credits no remat recompute; N EXCLUDES "
                "the embedding/position tables, whose lookups pay no "
                "per-token matmul FLOPs — the tied head shares the "
                "embedding; both N's are in lm_tpu.json) over the "
                "measured ceiling"
                if ceiling
                else "; MFU* is dashed — no measured roofline record; "
                "run tools/roofline_bench --write-docs first"
            )
            + ". The `params` column is total parameters.\n\n" + table + "\n\n"
            + (
                "## Generation (KV-cache greedy decode, one compiled "
                "scan)\n\n" + render_decode(decode_rows) + "\n\n"
                "Decode config gaps now track their KV-cache traffic "
                "ratios (full:gqa2 = 4× cache → ~2.3× time; the "
                "balance is shared weight/embedding reads). The "
                "round-4 record showed decode-full 15× gqa2 — that "
                "was the layer `lax.scan` double-buffering the whole "
                "stacked cache every token (xs→ys copies); "
                "`GPTLM.decode_step` now unrolls the layer loop "
                "(939→306 µs/token at c=1024, 2311→191 at c=4096 in "
                "the isolation benches; decode graphs are tiny, so "
                "compile time is unaffected).\n\n"
                if decode_rows
                else ""
            )
            + "Reading the MFU columns: the measured roofline "
            "(roofline_tpu.md) showed the tunneled chip sustains "
            "~98% of spec peak on pure matmul chains — the round-3 "
            "claim that 'the environment pins MFU at 1-2.5%' was a "
            "measurement artifact (the ~100 ms dispatch+fetch "
            "roundtrip was being divided into every step; the "
            "two-point method cancels it). What remains between "
            "these MFU* numbers and 100% is the WORKLOAD: toy "
            "widths (d=256-1024 matmuls tile the MXU poorly next "
            "to the roofline's 4096² chains), attention/layernorm/"
            "loss bandwidth-bound phases, and per-step optimizer "
            "traffic. Compare configs against each other AND "
            "against MFU*=100 — both comparisons are now "
            "meaningful. (Round 6: MFU† switched its N from total to "
            "non-embedding parameters — the scaling-book reading; at "
            "d=256 the 8192-entry table was 39% of N, so those rows' "
            "MFU† dropped by roughly that fraction. Step times are "
            "unchanged.)\n"
        )


if __name__ == "__main__":
    main()
