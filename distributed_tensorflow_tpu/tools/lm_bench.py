"""On-chip LM training benchmark: throughput (tokens/sec) + MFU per config.

The reference's method was measure-everything-and-publish — every mode has
an s/epoch number in its experiment log (reference README.md:13-15,38-40).
Round 2 built the whole GPT surface and measured none of it (VERDICT
round-2 missing #1); this tool closes that: it times `make_lm_train_step`
on the real chip with the only two disciplines that give truthful numbers
here (CLAUDE.md):

- ``steps`` train steps amortized inside ONE compiled dispatch (a
  ``lax.scan`` whose carry is the optimizer state — each step depends on
  the previous params, so nothing hoists), resolving per-step time far
  below the ~12 ms tunnel dispatch floor;
- a D2H value fetch (the final step's loss) as the execution barrier.

MFU = compiled-FLOPs-per-step (XLA's own cost model, via
``tools/cost_analysis.analyze_lm`` — the same program, not a hand
formula) / measured step time / chip peak FLOPs.

Usage::

    python -m distributed_tensorflow_tpu.tools.lm_bench            # full grid
    python -m distributed_tensorflow_tpu.tools.lm_bench --steps 16 \
        --configs gpt-s-L512-xla gpt-s-L512-flash

Prints a markdown table and a one-line JSON summary;
``docs/benchmarks/lm_tpu.md`` + ``lm_tpu.json`` are regenerated from this
tool's output (``--write-docs``).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import optax
from jax import lax

from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.tools.cost_analysis import _chip_peaks, analyze_lm

# Each entry: model kwargs + batch. Two (L, d, layers) points, and at the
# long-L point the attention-variant axis (xla / flash / flash+window /
# GQA) the round-2 verdict asked to separate.
# Batch sizes chosen to FILL the chip (MFU collapses when per-step matmuls
# are too small to tile the MXU — B=2 toy batches measured 1-2% MFU).
CONFIGS = {
    # short-context point: d=256, 4 layers, L=512
    "gpt-s-L512-xla": dict(
        batch=32,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=512),
    ),
    "gpt-s-L512-flash": dict(
        batch=32,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=512,
            attention_impl="flash",
        ),
    ),
    # long-context point: same model at L=2048
    "gpt-s-L2048-xla": dict(
        batch=8,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=2048),
    ),
    "gpt-s-L2048-flash": dict(
        batch=8,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=2048,
            attention_impl="flash",
        ),
    ),
    "gpt-s-L2048-flash-W512": dict(
        batch=8,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=2048,
            attention_impl="flash", window=512,
        ),
    ),
    "gpt-s-L2048-flash-gqa2": dict(
        batch=8,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, num_kv_heads=2,
            max_len=2048, attention_impl="flash",
        ),
    ),
    # bigger-model points: d=512 and d=1024 (wider matmuls → real MFU)
    "gpt-m-L1024-flash": dict(
        batch=16,
        model=dict(
            model_dim=512, num_layers=8, num_heads=8, max_len=1024,
            attention_impl="flash",
        ),
    ),
    "gpt-l-L1024-flash": dict(
        batch=8,
        model=dict(
            model_dim=1024, num_layers=8, num_heads=16, max_len=1024,
            attention_impl="flash",
        ),
    ),
}
_VOCAB = 8192

# Generation (KV-cache decode) configs: one scan-compiled greedy_decode
# dispatch per timing — prefill 256 prompt tokens, decode 256 more. The
# variant axis: full-length cache vs rolling windowed cache (O(W) slots)
# vs GQA (cache at Hkv width, grouped-einsum attend — no repeat).
DECODE_CONFIGS = {
    "decode-full": dict(
        batch=8, prompt=256, max_new=256,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=1024),
    ),
    "decode-window256": dict(
        batch=8, prompt=256, max_new=256,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=1024,
            window=256,
        ),
    ),
    "decode-gqa2": dict(
        batch=8, prompt=256, max_new=256,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, num_kv_heads=2,
            max_len=1024,
        ),
    ),
    "decode-long-full": dict(
        batch=4, prompt=256, max_new=256,
        model=dict(model_dim=256, num_layers=4, num_heads=8, max_len=4096),
    ),
    "decode-long-window256": dict(
        batch=4, prompt=256, max_new=256,
        model=dict(
            model_dim=256, num_layers=4, num_heads=8, max_len=4096,
            window=256,
        ),
    ),
}


def bench_decode(name: str, *, seed: int = 0) -> dict:
    spec = DECODE_CONFIGS[name]
    model = GPTLM(vocab_size=_VOCAB, **spec["model"])
    b, p_len, max_new = spec["batch"], spec["prompt"], spec["max_new"]
    params = model.init(seed=1)
    prompt = jax.random.randint(
        jax.random.key(seed), (b, p_len), 0, _VOCAB, jnp.int32
    )
    gen = jax.jit(lambda pr, t: model.greedy_decode(pr, t, max_new))
    out = gen(params, prompt)
    _ = int(out[-1, -1])  # compile + D2H barrier
    t0 = time.perf_counter()
    out = gen(params, prompt)
    _ = int(out[-1, -1])
    dt = time.perf_counter() - t0
    return {
        "config": name,
        "batch": b,
        "prompt": p_len,
        "max_new": max_new,
        "cache_len": model.cache_len,
        "ms_per_token": round(dt * 1e3 / max_new, 3),
        "gen_tokens_per_sec": round(b * max_new / dt, 1),
    }


def render_decode(rows) -> str:
    cols = ["config", "B", "prompt", "new", "cache", "ms/token", "gen tok/s"]
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if "error" in r:
            out.append(f"| {r['config']} | error: {r['error']} |" + " |" * 5)
            continue
        out.append(
            "| {config} | {batch} | {prompt} | {max_new} | {cache_len} | "
            "{ms_per_token:.2f} | {gen_tokens_per_sec:,.0f} |".format(**r)
        )
    return "\n".join(out)


def bench_config(
    name: str, *, steps: int = 32, lr: float = 1e-3, seed: int = 0
) -> dict:
    spec = CONFIGS[name]
    model = GPTLM(vocab_size=_VOCAB, **spec["model"])
    b, l = spec["batch"], model.max_len
    params = model.init(seed=1)
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.key(seed), (b, l), 0, _VOCAB, jnp.int32
    )

    @jax.jit
    def epoch(params, opt_state, tokens):
        def body(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(model.loss)(params, tokens)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = lax.scan(
            body, (params, opt_state), None, length=steps
        )
        return params, opt_state, losses

    p, o, losses = epoch(params, opt_state, tokens)  # compile + warm
    _ = float(losses[-1])  # D2H barrier (CLAUDE.md timing trap)
    t0 = time.perf_counter()
    p, o, losses = epoch(params, opt_state, tokens)
    final_loss = float(losses[-1])
    dt = time.perf_counter() - t0

    step_ms = dt * 1e3 / steps
    tokens_per_sec = b * l * steps / dt
    row = {
        "config": name,
        "batch": b,
        "seq_len": l,
        "steps_per_dispatch": steps,
        "step_ms": round(step_ms, 3),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "final_loss": round(final_loss, 4),
    }
    # MFU from the XLA cost model of the SAME single step program.
    report = analyze_lm(model, batch_size=b, optimizer=opt)
    row["flops_per_step"] = report["flops_per_step"]
    row["param_count"] = report["param_count"]
    peaks = _chip_peaks(jax.devices()[0])
    if peaks and report["flops_per_step"]:
        achieved = report["flops_per_step"] / (dt / steps)
        row["mfu_pct"] = round(100 * achieved / peaks["flops"], 2)
    else:
        row["mfu_pct"] = None
    return row


def run(configs=None, *, steps: int = 32) -> list[dict]:
    rows = []
    for name in configs or CONFIGS:
        try:
            rows.append(bench_config(name, steps=steps))
        except Exception as exc:  # noqa: BLE001 — record, keep sweeping
            rows.append(
                {"config": name, "error": f"{type(exc).__name__}: {exc}"[:200]}
            )
    return rows


def render(rows) -> str:
    cols = [
        "config", "B", "L", "step (ms)", "tokens/s", "MFU %", "params",
    ]
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if "error" in r:
            out.append(f"| {r['config']} | error: {r['error']} |" + " |" * 5)
            continue
        out.append(
            "| {config} | {batch} | {seq_len} | {step_ms:.2f} | "
            "{tokens_per_sec:,.0f} | {mfu} | {param_count:,} |".format(
                mfu=("%.1f" % r["mfu_pct"]) if r["mfu_pct"] is not None else "—",
                **r,
            )
        )
    return "\n".join(out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--configs", nargs="+", default=None, choices=sorted(CONFIGS))
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument(
        "--write-docs",
        action="store_true",
        help="regenerate docs/benchmarks/lm_tpu.{md,json}",
    )
    ap.add_argument(
        "--decode",
        action="store_true",
        help="also run the KV-cache generation configs",
    )
    args = ap.parse_args(argv)
    rows = run(args.configs, steps=args.steps)
    device = jax.devices()[0].device_kind
    print(f"device: {device}  steps/dispatch: {args.steps}")
    table = render(rows)
    print(table)
    decode_rows = []
    if args.decode:
        for name in DECODE_CONFIGS:
            try:
                decode_rows.append(bench_decode(name))
            except Exception as exc:  # noqa: BLE001
                decode_rows.append(
                    {"config": name,
                     "error": f"{type(exc).__name__}: {exc}"[:200]}
                )
        print(render_decode(decode_rows))
    payload = {
        "rows": rows, "decode_rows": decode_rows, "device": device,
        "backend": jax.default_backend(),
    }
    print(json.dumps(payload))
    if args.write_docs:
        root = os.path.join(os.path.dirname(__file__), "..", "..", "docs", "benchmarks")
        root = os.path.abspath(root)
        json_path = os.path.join(root, "lm_tpu.json")
        if not decode_rows and os.path.exists(json_path):
            # A regeneration run without --decode must not erase the decode
            # record — carry the previous rows forward.
            try:
                with open(json_path) as f:
                    decode_rows = json.load(f).get("decode_rows", [])
                payload["decode_rows"] = decode_rows
            except Exception:
                pass
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=1)
        cmd_flags = f"--steps {args.steps}" + (" --decode" if args.decode else "")
        with open(os.path.join(root, "lm_tpu.md"), "w") as f:
            f.write(
                "# LM training on one TPU chip\n\n"
                f"Generated by `python -m distributed_tensorflow_tpu.tools."
                f"lm_bench {cmd_flags} --write-docs` on {device} "
                "(bf16 matmuls, adam, vocab 8192; "
                f"{args.steps} steps amortized per dispatch, D2H-barrier "
                "timing; MFU = XLA-counted FLOPs / measured step time / "
                "chip peak).\n\n" + table + "\n\n"
                + (
                    "## Generation (KV-cache greedy decode, one compiled "
                    "scan)\n\n" + render_decode(decode_rows) + "\n\n"
                    if decode_rows
                    else ""
                )
                + "Reading the MFU column: it is computed against the v5e "
                "SPEC peak (197 bf16 TFLOPS). The tunneled chip in this "
                "environment delivers a single-digit-TFLOPS effective "
                "ceiling on EVERY workload — the whole-epoch Pallas MLP "
                "kernel's 10M ex/s headline is likewise ~2.5% of spec "
                "peak, and the flash kernel's fastest attention dispatch "
                "sustains ~15 TFLOPS — and MFU here is batch-invariant "
                "(4x the batch moved tokens/s not at all), i.e. the "
                "environment, not arithmetic shape, pins it. Compare "
                "configs against each other; treat the absolute MFU as "
                "this environment's ceiling, not the kernels'.\n"
            )
        print(f"wrote {root}/lm_tpu.md and lm_tpu.json")


if __name__ == "__main__":
    main()
