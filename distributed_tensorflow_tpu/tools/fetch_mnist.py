"""Fetch the real MNIST IDX files so the convergence oracle can run.

The reference trained on the real MNIST bytes
(``input_data.read_data_sets("MNIST_data/", ...)``, reference
tfsingle.py:13-14) and its headline numbers — 0.72 single/sync, 0.80
async — are accuracies on that data. This repo's development containers
are zero-egress, so the suite trains on the deterministic synthetic
MNIST and `tests/integration/test_oracles.py::test_real_mnist_convergence_oracle`
auto-skips until the IDX quartet exists. On ANY egress-capable machine,
one line closes that gap::

    python -m distributed_tensorflow_tpu.tools.fetch_mnist

then::

    RUN_SLOW=1 python -m pytest tests/integration/test_oracles.py \
        -k real_mnist -q

Downloads the four gzipped IDX files into ``MNIST_data/`` (or
``--data-dir``/``$MNIST_DATA_DIR``), tries several long-lived mirrors in
order, validates each file's IDX magic number and item count before
keeping it, and is idempotent (present-and-valid files are skipped).
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys
import urllib.request

# (filename, expected magic, expected item count)
_FILES = (
    ("train-images-idx3-ubyte", 2051, 60_000),
    ("train-labels-idx1-ubyte", 2049, 60_000),
    ("t10k-images-idx3-ubyte", 2051, 10_000),
    ("t10k-labels-idx1-ubyte", 2049, 10_000),
)

# Mirrors in preference order. The canonical yann.lecun.com host has been
# intermittently 403 for years; the GCS CVDF mirror is the stable one.
_MIRRORS = (
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
)


def _valid(path: str, magic: int, count: int) -> bool:
    try:
        with open(path, "rb") as f:
            got_magic, got_count = struct.unpack(">II", f.read(8))
        return got_magic == magic and got_count == count
    except (OSError, struct.error):
        return False


def fetch(data_dir: str = "MNIST_data", print_fn=print) -> bool:
    """Download any missing/invalid IDX files into ``data_dir``. Returns
    True when all four are present and valid afterwards."""
    os.makedirs(data_dir, exist_ok=True)
    ok = True
    for name, magic, count in _FILES:
        dest = os.path.join(data_dir, name)
        if _valid(dest, magic, count):
            print_fn(f"{name}: present and valid, skipping")
            continue
        done = False
        for mirror in _MIRRORS:
            url = mirror + name + ".gz"
            try:
                print_fn(f"{name}: fetching {url}")
                with urllib.request.urlopen(url, timeout=60) as resp:
                    raw = gzip.decompress(resp.read())
                tmp = dest + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(raw)
                if not _valid(tmp, magic, count):
                    os.remove(tmp)
                    print_fn(f"{name}: {mirror} served invalid bytes")
                    continue
                os.replace(tmp, dest)
                print_fn(f"{name}: ok ({len(raw)} bytes)")
                done = True
                break
            except Exception as exc:  # noqa: BLE001 — try the next mirror
                print_fn(f"{name}: {mirror} failed ({exc})")
        if not done:
            ok = False
    return ok


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--data-dir",
        default=os.environ.get("MNIST_DATA_DIR", "MNIST_data"),
        help="target directory (default: $MNIST_DATA_DIR or MNIST_data)",
    )
    args = parser.parse_args(argv)
    if fetch(args.data_dir):
        print(
            "all four IDX files ready — run: RUN_SLOW=1 python -m pytest "
            "tests/integration/test_oracles.py -k real_mnist -q"
        )
        return 0
    print("some files could not be fetched; see messages above", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
