"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A brand-new JAX/XLA/Pallas framework providing the capabilities of the reference
TF1 parameter-server training suite (``ijustloveses/distributed_tensorflow``):
single-device training, synchronous data-parallel training, asynchronous
data-parallel training, and multi-host distribution — re-designed TPU-first.

Architecture stance (see SURVEY.md §7): the reference's parameter-server star
topology (``tf.train.Server`` + ``replica_device_setter``, reference
tfdist_between.py:17,32-35) is replaced by flat SPMD over a
``jax.sharding.Mesh``: parameters live replicated on chips, batches are sharded
over the ``data`` mesh axis, and gradient aggregation is an XLA all-reduce over
ICI — there is no parameter server in the loop.

Layer map (mirrors SURVEY.md §1):

=====  =================================  =========================================
Layer  Reference                          This framework
=====  =================================  =========================================
L0     TF 1.2.1 C++ runtime (gRPC/CUDA)   XLA:TPU via jax.jit + native C++ runtime
                                          helpers (``runtime/``)
L1     ClusterSpec/Server bootstrap       ``cluster.py`` → jax.distributed
L2     replica_device_setter placement    ``parallel/mesh.py`` Mesh + PartitionSpec
L3     graph-built MLP                    ``models/`` pure functions
L4     (Sync)GradientDescentOptimizer     ``ops/optim.py`` + collective aggregation
L5     tf.train.Supervisor                ``train/supervisor.py`` (+ orbax ckpt)
L6     training loop + summaries          ``train/trainer.py`` + ``utils/summary.py``
L7     nohup-per-task launch              ``launch.py`` / example scripts
=====  =================================  =========================================
"""

__version__ = "0.1.0"

from distributed_tensorflow_tpu import config  # noqa: F401
from distributed_tensorflow_tpu.config import ClusterConfig, TrainConfig  # noqa: F401


_LAZY_EXPORTS = {
    "MLP": ("distributed_tensorflow_tpu.models", "MLP"),
    "CNN": ("distributed_tensorflow_tpu.models", "CNN"),
    "LSTMClassifier": ("distributed_tensorflow_tpu.models", "LSTMClassifier"),
    "TransformerClassifier": (
        "distributed_tensorflow_tpu.models",
        "TransformerClassifier",
    ),
    "GPTLM": ("distributed_tensorflow_tpu.models", "GPTLM"),
    "build_model": ("distributed_tensorflow_tpu.models", "build_model"),
    "ShardedDataParallel": (
        "distributed_tensorflow_tpu.parallel",
        "ShardedDataParallel",
    ),
    "Predictor": ("distributed_tensorflow_tpu.inference", "Predictor"),
    "TextServer": ("distributed_tensorflow_tpu.serve", "TextServer"),
    "GenerationConfig": (
        "distributed_tensorflow_tpu.serve",
        "GenerationConfig",
    ),
    "ReplicaRouter": (
        "distributed_tensorflow_tpu.serve_fleet",
        "ReplicaRouter",
    ),
    "local_fleet": ("distributed_tensorflow_tpu.serve_fleet", "local_fleet"),
    "read_data_sets": ("distributed_tensorflow_tpu.data", "read_data_sets"),
    "make_mesh": ("distributed_tensorflow_tpu.parallel", "make_mesh"),
    "SingleDevice": ("distributed_tensorflow_tpu.parallel", "SingleDevice"),
    "SyncDataParallel": ("distributed_tensorflow_tpu.parallel", "SyncDataParallel"),
    "AsyncDataParallel": ("distributed_tensorflow_tpu.parallel", "AsyncDataParallel"),
    "flash_attention": (
        "distributed_tensorflow_tpu.ops.pallas_attention",
        "flash_attention",
    ),
    "Trainer": ("distributed_tensorflow_tpu.train", "Trainer"),
    "Supervisor": ("distributed_tensorflow_tpu.train", "Supervisor"),
    "build_trainer": ("distributed_tensorflow_tpu.launch", "build_trainer"),
    "bootstrap": ("distributed_tensorflow_tpu.cluster", "bootstrap"),
}


def __getattr__(name):
    """Lazy top-level API (keeps `import distributed_tensorflow_tpu` cheap —
    no jax import until something that needs it is touched)."""
    try:
        module, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value  # cache: next access skips __getattr__
    return value
