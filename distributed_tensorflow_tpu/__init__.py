"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A brand-new JAX/XLA/Pallas framework providing the capabilities of the reference
TF1 parameter-server training suite (``ijustloveses/distributed_tensorflow``):
single-device training, synchronous data-parallel training, asynchronous
data-parallel training, and multi-host distribution — re-designed TPU-first.

Architecture stance (see SURVEY.md §7): the reference's parameter-server star
topology (``tf.train.Server`` + ``replica_device_setter``, reference
tfdist_between.py:17,32-35) is replaced by flat SPMD over a
``jax.sharding.Mesh``: parameters live replicated on chips, batches are sharded
over the ``data`` mesh axis, and gradient aggregation is an XLA all-reduce over
ICI — there is no parameter server in the loop.

Layer map (mirrors SURVEY.md §1):

=====  =================================  =========================================
Layer  Reference                          This framework
=====  =================================  =========================================
L0     TF 1.2.1 C++ runtime (gRPC/CUDA)   XLA:TPU via jax.jit + native C++ runtime
                                          helpers (``runtime/``)
L1     ClusterSpec/Server bootstrap       ``cluster.py`` → jax.distributed
L2     replica_device_setter placement    ``parallel/mesh.py`` Mesh + PartitionSpec
L3     graph-built MLP                    ``models/`` pure functions
L4     (Sync)GradientDescentOptimizer     ``ops/optim.py`` + collective aggregation
L5     tf.train.Supervisor                ``train/supervisor.py`` (+ orbax ckpt)
L6     training loop + summaries          ``train/trainer.py`` + ``utils/summary.py``
L7     nohup-per-task launch              ``launch.py`` / example scripts
=====  =================================  =========================================
"""

__version__ = "0.1.0"

from distributed_tensorflow_tpu import config  # noqa: F401
