"""Event → log-line renderers: the ONE place that knows the line formats.

The reference's log contract (SURVEY.md §5; tfdist_between.py:98-115) and
the framework's structured lifecycle lines (``Restart:``/``Resize:``/
``Rollback:``/``Preemption:``/``Restore:``, rounds 6-8) are rendered HERE,
from journal events — call sites emit an event and print the rendering,
never a hand-built f-string. ``tests/test_observability.py`` grep-lints
the package for structured-line literals outside this module, and pins
every renderer byte-for-byte against the pre-journal output.

``%``-formatting is deliberate: the step/epoch renderers must reproduce
the reference's ``%2d``/``%3d``/``%3.2f`` padding exactly (the C14 byte-
parity contract — downstream tooling that parsed the reference's stdout
keeps working).
"""

from __future__ import annotations

_FAILSTOP_TAIL = (
    "failing stop (checkpoints intact; newest valid step restores on the "
    "next launch)"
)


def _step(ev: dict) -> str:
    # The exact bytes of the reference's per-freq line
    # (tfdist_between.py:102-106): StepLogger printed five %-formatted
    # args which print() joined with single spaces.
    return (
        "Step: %d,  Epoch: %2d,  Batch: %3d of %3d,  Cost: %.4f,"
        "  AvgTime: %3.2fms"
        % (ev["step"], ev["epoch"], ev["batch"], ev["batch_count"],
           ev["cost"], ev["avg_ms"])
    )


def _epoch(ev: dict) -> list[str]:
    # Test-Accuracy keeps the reference's %2.2f (tfdist_between.py:109);
    # other per-epoch metrics (the LM's Test-Perplexity) use the %.4f
    # shape StepLogger.log_epoch_metric introduced.
    metric = ev.get("metric", "Test-Accuracy")
    if metric == "Test-Accuracy":
        head = "Test-Accuracy: %2.2f" % ev["value"]
    else:
        head = "%s: %.4f" % (metric, ev["value"])
    return [head, "Total Time: %3.2fs" % ev["total_time_s"]]


def _final(ev: dict) -> list[str]:
    return ["Final Cost: %.4f" % ev["cost"], "Done"]


def _restart(ev: dict) -> str:
    line = (
        f"Restart: restart={ev['restart']}/{ev['max_restarts']} "
        f"cause[{ev['cause']}] backoff_s={ev['backoff_s']:.1f}"
    )
    # Round 17 (independent members, train/elastic.py): which members
    # relaunched ALONE. Absent on gang restarts — round-7 lines stay
    # byte-identical.
    if ev.get("independent"):
        line += f" independent=True members=[{','.join(ev['members'])}]"
    return line


def _restart_exhausted(ev: dict) -> str:
    return (
        f"Restart: budget exhausted restarts={ev['restarts']}/"
        f"{ev['max_restarts']} cause[{ev['cause']}] — " + _FAILSTOP_TAIL
    )


def _resize(ev: dict) -> str:
    return (
        f"Resize: world={ev['world']} from={ev['from_world']} "
        f"min_workers={ev['min_workers']} direction={ev['direction']} "
        f"dropped=[{','.join(ev['dropped'])}] "
        f"rejoined=[{','.join(ev['rejoined'])}] "
        f"restart={ev['restart']}/{ev['max_restarts']}"
    )


def _resize_denied(ev: dict) -> str:
    return (
        f"Resize: denied world={ev['world']} "
        f"min_workers={ev['min_workers']} restarts={ev['restarts']}/"
        f"{ev['max_restarts']} cause[{ev['cause']}] — " + _FAILSTOP_TAIL
    )


def _rollback(ev: dict) -> str:
    # The anomaly class rides the event as "anomaly" (the journal's own
    # type key is "kind"); the line keeps the round-6 wording.
    return (
        f"Rollback: kind={ev['anomaly']} epoch={ev['epoch']} "
        f"detected_step={ev['detected_step']} "
        f"restored_step={ev['restored_step']} "
        f"rollback={ev['rollback']}/{ev['max_rollbacks']} "
        "data_window=skipped"
    )


def _rollback_compiled(ev: dict) -> str:
    return (
        "Rollback: kind=nan dispatch=compiled save=skipped "
        "(state not checkpointed; last good step kept)"
    )


def _preemption(ev: dict) -> str:
    # Round 22: a guard asked for off the main thread never arms — say so
    # once instead of being discovered at kill time.
    if ev.get("disarmed"):
        return f"Preemption: disarmed ({ev['disarmed']})"
    line = (
        f"Preemption: signal={ev['signal']} stop_requested=1 — finishing "
        "the current epoch, saving, exiting (signal again to force)"
    )
    # Round 22 (emergency snapshot): the step the handler persisted
    # immediately. Absent when nothing newer than disk existed (sync
    # mode, or the boundary save already landed) — the round-6 line
    # stays byte-identical.
    if ev.get("saved_step") is not None:
        line += f" saved_step={ev['saved_step']}"
    return line


def _heartbeat(ev: dict) -> str:
    # Round 22 (progress watchdog): normally journal-only — trainers emit
    # it without a print_fn; the renderer exists for obs_report replays.
    return f"Heartbeat: rank={ev.get('rank')} step={ev.get('step')}"


def _stall(ev: dict) -> str:
    # Round 22: the watchdog's verdict line — alive but not advancing
    # (the SIGSTOP / wedged-collective class rc= and health can't see).
    return (
        f"Stall: member={ev['member']} "
        f"heartbeat_age_s={ev['age_s']:.1f} "
        f"stall_after_s={ev['stall_after_s']:.1f} — killing and "
        "recovering through the elastic path"
    )


def _restore(ev: dict) -> str:
    return (
        f"Restore: global_batch={ev['global_batch']} preserved "
        f"(world={ev['from_world']}->{ev['world']}, config batch "
        f"{ev['config_batch']}x{ev['world']}={ev['config_global']} "
        f"overridden, per-replica batch {ev['per_replica']})"
    )


def _replica_dead(ev: dict) -> str:
    return (
        f"Replica: dead name={ev['replica']} verdict={ev['verdict']} "
        f"rerouted={ev['rerouted']} restart={ev['attempt']}/"
        f"{ev['max_restarts']}"
    )


def _replica_relaunch(ev: dict) -> str:
    return (
        f"Replica: relaunch name={ev['replica']} "
        f"restart={ev['attempt']}/{ev['max_restarts']} "
        f"backoff_s={ev['backoff_s']:.1f}"
    )


def _replica_benched(ev: dict) -> str:
    return (
        f"Replica: benched name={ev['replica']} restarts={ev['restarts']}/"
        f"{ev['max_restarts']} — fleet continues on the remaining replicas"
    )


def _fleet_below_floor(ev: dict) -> str:
    return (
        f"Fleet: below floor replicas={ev['replicas']} "
        f"min_replicas={ev['min_replicas']} cause[{ev['cause']}] — "
        "failing stop (unserved requests stay with the caller; nothing "
        "durable is lost)"
    )


def _serve_drain(ev: dict) -> str:
    return (
        f"Drain: admission closed residents={ev.get('residents')} "
        f"queued={ev.get('queued')}"
    )


def _weight_swap(ev: dict) -> str:
    return (
        f"Swap: weights step={ev.get('step')} from_step={ev.get('from_step')}"
        f" source={ev.get('source')}"
    )


def _mailbox_corrupt(ev: dict) -> str:
    # Round 19 (CRC-hardened mailboxes): a committed-but-corrupt post.
    # "skipped" = delta mailbox (watermark advanced past it, never
    # consumed); "quarantined" = fleet mailbox (removed, never delivered).
    line = (
        f"Mailbox: corrupt mailbox={ev['mailbox']} file={ev['file']} "
        f"reason={ev['reason']} action={ev['action']}"
    )
    if "peer" in ev:
        line += f" peer={ev['peer']} round={ev['round']}"
    if "box" in ev:
        line += f" box={ev['box']}"
    return line


def _breaker_open(ev: dict) -> str:
    # Round 21 (router circuit breaker): routes divert immediately,
    # before the slower HttpHealth verdict; nothing charged to the
    # restart budget.
    return (
        f"Breaker: open replica={ev['replica']} failures={ev['failures']} "
        f"reason[{ev['reason']}] reset_s={ev['reset_s']:.1f}"
    )


def _breaker_half_open(ev: dict) -> str:
    return f"Breaker: half-open replica={ev['replica']} — probing one request"


def _breaker_close(ev: dict) -> str:
    return f"Breaker: close replica={ev['replica']}"


def _fleet_roles(ev: dict) -> str:
    # Round 23 (disaggregated fleet): the role map, recorded once at
    # router construction.
    roles = ev.get("roles") or {}
    body = " ".join(f"{k}={v}" for k, v in sorted(roles.items()))
    return f"Fleet: roles {body} migrate_dir={ev.get('migrate_dir')}"


def _request_migrated(ev: dict) -> str:
    return (
        f"Migrate: trace={ev.get('trace')} from={ev.get('from_replica')} "
        f"post={ev.get('post')} blocks={ev.get('blocks')} "
        f"nbytes={ev.get('nbytes')}"
    )


def _kv_migration(ev: dict) -> str:
    line = f"KV-migration: phase={ev.get('phase')} trace={ev.get('trace')}"
    for k in ("slot", "blocks", "nbytes", "wall_ms", "file", "reason"):
        if k in ev:
            line += f" {k}={ev[k]}"
    return line


def _failpoint(ev: dict) -> str:
    # Round 19 (train/failpoints.py): an injected fault fired.
    return (
        f"Failpoint: name={ev['name']} fault={ev['fault']} hit={ev['hit']}"
    )


RENDERERS = {
    "step": _step,
    "epoch": _epoch,
    "final": _final,
    "restart": _restart,
    "restart_exhausted": _restart_exhausted,
    "resize": _resize,
    "resize_denied": _resize_denied,
    "rollback": _rollback,
    "rollback_compiled": _rollback_compiled,
    "preemption": _preemption,
    "heartbeat": _heartbeat,
    "stall": _stall,
    "restore": _restore,
    "replica_dead": _replica_dead,
    "replica_relaunch": _replica_relaunch,
    "replica_benched": _replica_benched,
    "fleet_below_floor": _fleet_below_floor,
    "serve_drain": _serve_drain,
    "weight_swap": _weight_swap,
    "mailbox_corrupt": _mailbox_corrupt,
    "failpoint": _failpoint,
    "breaker_open": _breaker_open,
    "breaker_half_open": _breaker_half_open,
    "breaker_close": _breaker_close,
    "fleet_roles": _fleet_roles,
    "request_migrated": _request_migrated,
    "kv_migration": _kv_migration,
}


def render(kind: str, ev: dict) -> list[str]:
    """The stdout line(s) for an event of ``kind`` (most kinds render one
    line; epoch/final render two, matching the reference's pairs)."""
    out = RENDERERS[kind](ev)
    return [out] if isinstance(out, str) else list(out)


def emit_line(
    kind: str,
    *,
    journal=None,
    print_fn=None,
    **fields,
) -> dict:
    """The event-first logging primitive: journal the event (NullJournal
    when none attached — the dict is still built), then print the line(s)
    RENDERED FROM IT. Returns the event. Every structured stdout line in
    the framework goes through here (grep-lint-enforced)."""
    if journal is None:
        from distributed_tensorflow_tpu.observability import journal as _j

        journal = _j.get_journal()
    ev = journal.emit(kind, **fields)
    if print_fn is not None:
        for line in render(kind, ev):
            print_fn(line)
    return ev
