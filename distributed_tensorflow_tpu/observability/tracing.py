"""Trace context: correlate every journal event of one logical operation.

The reference's stdout had no request/run identity at all — a timing line
could not be attributed to anything smaller than "the process" (reference
tfdist_between.py:98-110). The round-10 journal made each *event* typed;
this module makes them *joinable*: a trace id names one logical operation
(a serving request's submit→queue→prefill→decode→completion life, a
trainer run's epochs+dispatches+checkpoints, a gang incarnation), and
every journal event that belongs to it carries ``trace=<id>``.

Two propagation styles, matching the two shapes of instrumented code:

- **Explicit** (concurrent operations interleaved on one thread — the
  serving scheduler, where one ``step()`` advances many requests): the
  component stores ``new_trace_id()`` per operation and passes
  ``trace=...`` into its emits. :class:`~serve.TextServer` does this per
  request; ``tools/obs_report.py --requests`` joins the events back into
  per-request timelines.
- **Ambient** (one operation per thread — a trainer run, a gang
  supervision loop): ``with tracing.trace():`` installs a thread-local
  current trace, and EVERY journal emit on that thread — including ones
  deep inside the Supervisor's checkpoint path and the SpanRecorder's
  span mirror, which never learned about tracing — is tagged
  automatically by :meth:`journal.NullJournal.emit`. Explicit ``trace=``
  fields always win over the ambient one.

Ids are 16 hex chars from ``os.urandom`` — unique across ranks without
coordination (no counters to collide when N processes journal into one
logdir). jax-free (lean-import convention), stdlib only.
"""

from __future__ import annotations

import binascii
import os
import threading

_local = threading.local()


def new_trace_id() -> str:
    """A fresh 64-bit trace id (16 hex chars), collision-safe across
    processes — no shared counter, so concurrent ranks never coordinate."""
    return binascii.hexlify(os.urandom(8)).decode("ascii")


def current_trace() -> str | None:
    """The innermost ambient trace id on this thread, or None."""
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


class trace:
    """Context manager installing an ambient trace id on this thread::

        with tracing.trace() as tid:       # or tracing.trace("fixed-id")
            journal.emit("step", ...)       # carries trace=tid

    Nests (inner traces shadow outer ones); re-entrant per thread; never
    leaks across threads (each has its own stack)."""

    def __init__(self, trace_id: str | None = None):
        self.trace_id = trace_id if trace_id is not None else new_trace_id()

    def __enter__(self) -> str:
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        stack.append(self.trace_id)
        return self.trace_id

    def __exit__(self, *exc) -> None:
        _local.stack.pop()
