"""Structured event journal: typed, append-only JSONL (SURVEY.md §5).

The reference's only machine surface was stdout (tfdist_between.py:98-110);
everything downstream of it — the experiment tables in the reference
README, the parity oracles here — was grep'd out of log files. This module
is the machine-readable record those greps were standing in for: every
structured signal the framework emits (Step/Cost/AvgTime lines, lifecycle
``Restart:``/``Resize:``/``Rollback:``/``Preemption:``/``Restore:`` lines,
serving admissions/completions, checkpoint saves, metrics snapshots, host
spans) is ONE JSON object per line in ``<logdir>/events.jsonl``, tagged
with wall time, rank/world, a run id, and — when a trace context is
active or a ``trace=`` field is passed — the trace id that joins the
event to its logical operation (:mod:`observability.tracing`).

Write discipline: one event = one ``os.write()`` of one ``\\n``-terminated
line on an ``O_APPEND`` descriptor — concurrent writers (a gang of ranks
sharing a logdir) interleave whole lines, never bytes. The raw-fd write
matters: buffered text streams split writes larger than their buffer
(8 KiB by default), so a big ``metrics`` snapshot event could tear across
a concurrent append — ``tests/test_observability.py``'s multi-writer
stress test pins >8 KiB events against exactly that. The reader
(:func:`read_events`) tolerates a torn final line (a killed process mid-
write), mirroring the checkpoint layer's crash-consistency stance.

Rotation (round 12, default OFF — existing journals are byte-identical):
``EventJournal(rotate_bytes=N)`` caps the active file; when an append
would push it past ``N`` the file is renamed to ``events.jsonl.<k>``
(``.1`` oldest) and a fresh active file starts. :func:`read_events` and
:func:`journal_segments` span the rotated chain transparently. Rotation
is a single-writer feature: concurrent appenders sharing one path must
keep it off (the rename would swap the file out from under their fds).

The stdout bytes remain byte-identical to the reference format: renderers
in :mod:`observability.format` produce the log lines FROM these events
(the C14 parity contract — see ``utils/logging.StepLogger``), so the
journal is a superset of stdout, never a replacement.

jax-free by design (the lean-import convention, CLAUDE.md round 8/9):
this module and the whole ``observability`` package import and run on a
container with no working jax — the elastic driver and the reader tooling
live there.
"""

from __future__ import annotations

import json
import os
import re
import time

from distributed_tensorflow_tpu.observability import tracing
from distributed_tensorflow_tpu.train import failpoints

_SEGMENT = re.compile(r"\.(\d+)$")


class NullJournal:
    """The unconfigured default: ``emit`` builds and returns the event
    dict (so renderers can still format lines from it) but writes
    nothing. Trainers construct their log lines through this path even
    when no journal is attached — one code path, zero I/O."""

    path = None

    def emit(self, kind: str, **fields) -> dict:
        ev = {"ts": time.time(), "kind": kind}
        ambient = tracing.current_trace()
        if ambient is not None and "trace" not in fields:
            ev["trace"] = ambient
        ev.update(fields)
        return ev

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class EventJournal(NullJournal):
    """Append-only JSONL event stream.

    Every event carries ``ts`` (wall clock), ``kind``, and — when set —
    ``rank``/``world``/``run`` tags, the ambient trace id
    (:mod:`~.tracing`, unless an explicit ``trace=`` field overrides),
    then the caller's fields. Field values must be JSON-serializable
    (the writer coerces stray numpy scalars via their ``item()``)."""

    def __init__(
        self,
        path: str,
        *,
        rank: int | None = None,
        world: int | None = None,
        run_id: str | None = None,
        rotate_bytes: int = 0,
        fsync: bool = False,
        clock=time.time,
    ):
        self.path = path
        self.rank = rank
        self.world = world
        self.run_id = run_id
        # Round 21 (opt-in; DTF_JOURNAL_FSYNC=1): fsync after EVERY
        # append, so a kill inside emit() can no longer lose the final
        # line (docs/known_issues.md). Default off — the write path and
        # bytes are identical, only durability timing changes.
        self.fsync = bool(fsync)
        self.rotate_bytes = int(rotate_bytes)
        if self.rotate_bytes < 0:
            raise ValueError(
                f"rotate_bytes must be >= 0 (0 disables), got {rotate_bytes}"
            )
        self._clock = clock
        self._fd = None
        self._size = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    @classmethod
    def in_dir(cls, logdir: str, **kw) -> "EventJournal":
        """The conventional location: ``<logdir>/events.jsonl``."""
        return cls(os.path.join(logdir, "events.jsonl"), **kw)

    def _file(self) -> int:
        if self._fd is None:
            # O_APPEND: the kernel serializes whole-buffer appends, so
            # multi-process journals interleave whole lines — provided
            # each line is ONE os.write (see the module docstring).
            self._fd = os.open(
                self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
            )
            self._size = os.fstat(self._fd).st_size
        return self._fd

    @staticmethod
    def _default(o):
        # numpy scalars/arrays without importing numpy: anything exposing
        # item() (0-d) or tolist() degrades to plain Python.
        if hasattr(o, "item") and getattr(o, "ndim", 1) == 0:
            return o.item()
        if hasattr(o, "tolist"):
            return o.tolist()
        raise TypeError(
            f"event field of type {type(o).__name__} is not JSON-serializable"
        )

    def _rotate(self) -> None:
        """Retire the active file as the next ``.k`` segment (``.1`` is
        the oldest). Single-writer only — see the module docstring."""
        failpoints.fire("journal.rotate")
        os.close(self._fd)
        self._fd = None
        taken = [
            int(_SEGMENT.search(seg).group(1))
            for seg in journal_segments(self.path)
            if seg != self.path
        ]
        os.replace(self.path, f"{self.path}.{max(taken, default=0) + 1}")

    def emit(self, kind: str, **fields) -> dict:
        # Failpoint before any I/O; fire() guards its own reentrancy, so
        # the `failpoint` event it journals cannot recurse through here.
        failpoints.fire("journal.append")
        ev: dict = {"ts": self._clock(), "kind": kind}
        if self.rank is not None:
            ev["rank"] = int(self.rank)
        if self.world is not None:
            ev["world"] = int(self.world)
        if self.run_id is not None:
            ev["run"] = self.run_id
        ambient = tracing.current_trace()
        if ambient is not None and "trace" not in fields:
            ev["trace"] = ambient
        ev.update(fields)
        data = (json.dumps(ev, default=self._default) + "\n").encode("utf-8")
        fd = self._file()
        if (
            self.rotate_bytes
            and self._size
            and self._size + len(data) > self.rotate_bytes
        ):
            self._rotate()
            fd = self._file()
        os.write(fd, data)  # ONE write = one line: the atomicity contract
        self._size += len(data)
        if self.fsync:
            self.flush()
        return ev

    def flush(self) -> None:
        if self._fd is not None:
            try:
                os.fsync(self._fd)
            except OSError:  # pragma: no cover — exotic filesystems
                pass

    def close(self) -> None:
        if self._fd is not None:
            self.flush()
            os.close(self._fd)
            self._fd = None


def journal_segments(path: str) -> list[str]:
    """The on-disk chain of one journal, oldest→newest: rotated segments
    ``<path>.1..N`` (numeric order) then the active ``<path>``. Files
    that do not exist are omitted (a never-rotated journal is just
    ``[path]``)."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    nums = []
    if os.path.isdir(parent):
        for name in os.listdir(parent):
            if name.startswith(base + "."):
                m = _SEGMENT.search(name)
                if m and name == f"{base}.{m.group(1)}":
                    nums.append(int(m.group(1)))
    chain = [f"{path}.{n}" for n in sorted(nums)]
    if os.path.exists(path):
        chain.append(path)
    return chain


def _parse_segment(path: str, out: list, *, kind: str | None) -> None:
    with open(path, encoding="utf-8") as f:
        lines = f.read().split("\n")
    # A complete file ends with "\n", so split leaves a trailing "".
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail: the writer died mid-line
            raise ValueError(f"{path}:{i + 1}: corrupt event line") from None
        if kind is None or ev.get("kind") == kind:
            out.append(ev)


def read_events(path: str, *, kind: str | None = None) -> list[dict]:
    """Parse an ``events.jsonl`` (or a logdir containing one), spanning
    rotated segments (``events.jsonl.1..N`` oldest-first, then the active
    file) transparently. A torn final line — a writer killed mid-append —
    is skipped silently; a torn line anywhere else raises (that is
    corruption, not a crash tail). ``kind`` filters."""
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    segments = journal_segments(path)
    if not segments:
        # Preserve the single-file error shape for a missing journal.
        with open(path, encoding="utf-8"):
            pass  # pragma: no cover — open() raises above
    out: list[dict] = []
    for seg in segments:
        _parse_segment(seg, out, kind=kind)
    return out


def append_event(path: str, kind: str, **fields) -> dict:
    """One-shot append (open → emit → close) for tools that record a
    single measurement — the bench emitters use this so a crash between
    points never holds a descriptor open."""
    j = EventJournal(path)
    try:
        return j.emit(kind, **fields)
    finally:
        j.close()


# -- module default (process-wide wiring) -----------------------------------

_default: NullJournal = NullJournal()


def configure(
    logdir: str | None = None,
    *,
    path: str | None = None,
    rank: int | None = None,
    world: int | None = None,
    run_id: str | None = None,
    rotate_bytes: int = 0,
    fsync: bool = False,
) -> NullJournal:
    """Install the process-default journal (``<logdir>/events.jsonl``, or
    an explicit ``path``). Components that were not handed a journal
    explicitly fall back to this one; with neither, emission is a no-op
    (:class:`NullJournal`)."""
    global _default
    if _default is not None:
        _default.close()
    if path is None and logdir is None:
        _default = NullJournal()
    else:
        if path is None:
            path = os.path.join(logdir, "events.jsonl")
        _default = EventJournal(
            path, rank=rank, world=world, run_id=run_id,
            rotate_bytes=rotate_bytes, fsync=fsync,
        )
    return _default


def rank_journal_path(logdir: str, rank: int) -> str:
    """The per-rank journal convention for a gang sharing a logdir:
    ``<logdir>/events-rank<k>.jsonl``. One file per rank keeps rotation
    legal (single writer) and gives :mod:`observability.aggregate` clean
    per-rank timelines to merge; the driver keeps the plain
    ``events.jsonl``."""
    return os.path.join(logdir, f"events-rank{int(rank)}.jsonl")


def configure_from_env(
    rank: int | None = None, *, announce: bool = True, environ=None
) -> NullJournal:
    """Arm the process-default journal from the launcher-set env — the
    zero-code path for gang workers (``tools/launch_local.py`` exports
    these for every spawned task):

    - ``DTF_EVENTS_PATH`` — explicit journal path, or
    - ``DTF_JOURNAL_DIR`` — logdir; the journal lands at
      :func:`rank_journal_path` when a rank is known (the ``rank``
      argument, else ``DTF_RANK``), else ``events.jsonl``.

    ``DTF_WORLD_SIZE``/``DTF_RUN_ID`` tag events;
    ``DTF_JOURNAL_ROTATE_BYTES`` arms rotation;
    ``DTF_JOURNAL_FSYNC=1`` arms fsync-per-append (round 21 — the
    kill-in-append durability opt-in). With neither path knob
    set this is a no-op returning the current default — safe to call
    unconditionally. ``announce=True`` emits a ``worker_start`` event
    (pid + rank), which is how a per-rank journal shows its own restarts:
    every incarnation of the worker announces itself, so ``obs_report
    --gang`` sees one ``worker_start`` per (re)launch."""
    env = os.environ if environ is None else environ
    path = env.get("DTF_EVENTS_PATH")
    logdir = env.get("DTF_JOURNAL_DIR")
    if not path and not logdir:
        return _default
    if rank is None and env.get("DTF_RANK"):
        rank = int(env["DTF_RANK"])
    if not path:
        path = (
            rank_journal_path(logdir, rank)
            if rank is not None
            else os.path.join(logdir, "events.jsonl")
        )
    world = int(env["DTF_WORLD_SIZE"]) if env.get("DTF_WORLD_SIZE") else None
    j = configure(
        path=path,
        rank=rank,
        world=world,
        run_id=env.get("DTF_RUN_ID"),
        rotate_bytes=int(env.get("DTF_JOURNAL_ROTATE_BYTES", "0") or 0),
        fsync=env.get("DTF_JOURNAL_FSYNC", "") in ("1", "true"),
    )
    if announce:
        j.emit("worker_start", pid=os.getpid())
    return j


def get_journal() -> NullJournal:
    return _default


def emit(kind: str, **fields) -> dict:
    """Emit through the process-default journal."""
    return _default.emit(kind, **fields)
