"""Structured event journal: typed, append-only JSONL (SURVEY.md §5).

The reference's only machine surface was stdout (tfdist_between.py:98-110);
everything downstream of it — the experiment tables in the reference
README, the parity oracles here — was grep'd out of log files. This module
is the machine-readable record those greps were standing in for: every
structured signal the framework emits (Step/Cost/AvgTime lines, lifecycle
``Restart:``/``Resize:``/``Rollback:``/``Preemption:``/``Restore:`` lines,
serving admissions/completions, checkpoint saves, metrics snapshots, host
spans) is ONE JSON object per line in ``<logdir>/events.jsonl``, tagged
with wall time, rank/world, and a run id.

Write discipline: one event = one ``write()`` of one ``\\n``-terminated
line on an ``O_APPEND`` descriptor — concurrent writers (a gang of ranks
sharing a logdir) interleave whole lines, never bytes, for lines under
the pipe/page atomicity bound our events stay well inside. The reader
(:func:`read_events`) tolerates a torn final line (a killed process mid-
write), mirroring the checkpoint layer's crash-consistency stance.

The stdout bytes remain byte-identical to the reference format: renderers
in :mod:`observability.format` produce the log lines FROM these events
(the C14 parity contract — see ``utils/logging.StepLogger``), so the
journal is a superset of stdout, never a replacement.

jax-free by design (the lean-import convention, CLAUDE.md round 8/9):
this module and the whole ``observability`` package import and run on a
container with no working jax — the elastic driver and the reader tooling
live there.
"""

from __future__ import annotations

import json
import os
import time


class NullJournal:
    """The unconfigured default: ``emit`` builds and returns the event
    dict (so renderers can still format lines from it) but writes
    nothing. Trainers construct their log lines through this path even
    when no journal is attached — one code path, zero I/O."""

    path = None

    def emit(self, kind: str, **fields) -> dict:
        ev = {"ts": time.time(), "kind": kind}
        ev.update(fields)
        return ev

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class EventJournal(NullJournal):
    """Append-only JSONL event stream.

    Every event carries ``ts`` (wall clock), ``kind``, and — when set —
    ``rank``/``world``/``run`` tags, then the caller's fields. Field
    values must be JSON-serializable (the writer coerces stray numpy
    scalars via their ``item()``)."""

    def __init__(
        self,
        path: str,
        *,
        rank: int | None = None,
        world: int | None = None,
        run_id: str | None = None,
        clock=time.time,
    ):
        self.path = path
        self.rank = rank
        self.world = world
        self.run_id = run_id
        self._clock = clock
        self._f = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    @classmethod
    def in_dir(cls, logdir: str, **kw) -> "EventJournal":
        """The conventional location: ``<logdir>/events.jsonl``."""
        return cls(os.path.join(logdir, "events.jsonl"), **kw)

    def _file(self):
        if self._f is None:
            # O_APPEND via mode "a": the kernel serializes whole-buffer
            # appends, so multi-process journals interleave whole lines.
            self._f = open(self.path, "a", encoding="utf-8")
        return self._f

    @staticmethod
    def _default(o):
        # numpy scalars/arrays without importing numpy: anything exposing
        # item() (0-d) or tolist() degrades to plain Python.
        if hasattr(o, "item") and getattr(o, "ndim", 1) == 0:
            return o.item()
        if hasattr(o, "tolist"):
            return o.tolist()
        raise TypeError(
            f"event field of type {type(o).__name__} is not JSON-serializable"
        )

    def emit(self, kind: str, **fields) -> dict:
        ev: dict = {"ts": self._clock(), "kind": kind}
        if self.rank is not None:
            ev["rank"] = int(self.rank)
        if self.world is not None:
            ev["world"] = int(self.world)
        if self.run_id is not None:
            ev["run"] = self.run_id
        ev.update(fields)
        line = json.dumps(ev, default=self._default) + "\n"
        f = self._file()
        f.write(line)  # one write = one line: the atomicity contract
        f.flush()
        return ev

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:  # pragma: no cover — exotic filesystems
                pass

    def close(self) -> None:
        if self._f is not None:
            self.flush()
            self._f.close()
            self._f = None


def read_events(path: str, *, kind: str | None = None) -> list[dict]:
    """Parse an ``events.jsonl`` (or a logdir containing one). A torn
    final line — a writer killed mid-append — is skipped silently; a torn
    line anywhere else raises (that is corruption, not a crash tail).
    ``kind`` filters."""
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().split("\n")
    # A complete file ends with "\n", so split leaves a trailing "".
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail: the writer died mid-line
            raise ValueError(f"{path}:{i + 1}: corrupt event line") from None
        if kind is None or ev.get("kind") == kind:
            out.append(ev)
    return out


def append_event(path: str, kind: str, **fields) -> dict:
    """One-shot append (open → emit → close) for tools that record a
    single measurement — the bench emitters use this so a crash between
    points never holds a descriptor open."""
    j = EventJournal(path)
    try:
        return j.emit(kind, **fields)
    finally:
        j.close()


# -- module default (process-wide wiring) -----------------------------------

_default: NullJournal = NullJournal()


def configure(
    logdir: str | None = None,
    *,
    path: str | None = None,
    rank: int | None = None,
    world: int | None = None,
    run_id: str | None = None,
) -> NullJournal:
    """Install the process-default journal (``<logdir>/events.jsonl``, or
    an explicit ``path``). Components that were not handed a journal
    explicitly fall back to this one; with neither, emission is a no-op
    (:class:`NullJournal`)."""
    global _default
    if _default is not None:
        _default.close()
    if path is None and logdir is None:
        _default = NullJournal()
    else:
        if path is None:
            path = os.path.join(logdir, "events.jsonl")
        _default = EventJournal(path, rank=rank, world=world, run_id=run_id)
    return _default


def get_journal() -> NullJournal:
    return _default


def emit(kind: str, **fields) -> dict:
    """Emit through the process-default journal."""
    return _default.emit(kind, **fields)
