"""Gang-wide journal aggregation: N ranks' events.jsonl → one timeline.

The reference's experiment tables (reference README.md:38-40) were
assembled by a HUMAN reading N per-task log files side by side; the
round-10 journal made each process machine-readable but left the join to
grep. This module performs the join: it discovers every journal under a
gang logdir (the driver's ``events.jsonl``, the per-rank
``events-rank<k>.jsonl`` files ``journal.configure_from_env`` creates,
rotated segments included), aligns their clocks, and merges them into one
fleet timeline — the substrate for ``obs_report --gang`` and the
per-rank-track chrome trace where a restart or resize is visible on
every rank at the same instant.

Clock alignment: each journal's events carry its OWN host wall clock.
Within one host (launch_local) the clocks agree; across hosts they skew.
The estimator uses **shared gang lifecycle events** as anchors — a
``restart``/``resize``/``restart_exhausted``/``resize_denied`` (or an
explicit ``gang_sync``) with the same identifying fields names the same
physical instant wherever it was journaled, so for each journal the
median of ``ts_self − ts_reference`` over shared anchors is its clock
offset, subtracted before merging. Journals sharing no anchor with the
reference (the common single-host case: workers journal steps, the
driver journals restarts) get offset 0 — correct there, conservative
elsewhere.

jax-free (lean-import convention): runs on the driver host or any
machine the logdir was copied to.
"""

from __future__ import annotations

import os
import re
import statistics

from distributed_tensorflow_tpu.observability.journal import (
    journal_segments,
    read_events,
)

# Kinds that name ONE physical gang-wide instant in every journal that
# records them — the skew anchors, and the events mirrored onto every
# rank track in the chrome trace. The serving-fleet router's lifecycle
# kinds (round 16) ride along: they are recorded only in the router's
# journal, so they never act as cross-journal anchors, but they ARE
# fleet-wide moments the merged trace should show on every track.
GANG_KINDS = (
    "restart",
    "restart_exhausted",
    "resize",
    "resize_denied",
    "gang_sync",
    "replica_dead",
    "replica_relaunch",
    "replica_benched",
    "fleet_below_floor",
    "breaker_open",
    "breaker_half_open",
    "breaker_close",
    # Round 23 (disaggregated fleet): the role map is a one-shot
    # router-journal lifecycle moment, mirrored like the other fleet
    # kinds. Per-request migration kinds (request_migrated,
    # kv_migration) stay OUT, like request_route.
    "fleet_roles",
    # Round 22 (progress watchdog): "stall" is a true gang moment — the
    # driver's verdict on a frozen member, recorded once, mirrored on
    # every track. "heartbeat" rides in GANG_KINDS for the report's
    # per-rank last-progress join, but it is a PER-RANK stream, not a
    # shared instant — it is excluded from the skew anchors
    # (_ANCHOR_KINDS) and rendered as a local instant in the chrome
    # trace, per the round-19 rule that per-rank kinds must never anchor
    # (colliding keys would poison estimate_skew).
    "stall",
    "heartbeat",
)

# The skew-anchor subset of GANG_KINDS: kinds where ONE physical instant
# is recorded in MULTIPLE journals. Per-rank streams (heartbeat) never
# qualify.
_ANCHOR_KINDS = tuple(k for k in GANG_KINDS if k != "heartbeat")

_RANK_FILE = re.compile(r"^events-rank(\d+)\.jsonl$")


def discover_journals(logdir: str) -> dict:
    """Map journal label → path for every journal under ``logdir``:
    ``events.jsonl`` → ``driver``, ``events-rank<k>.jsonl`` → ``rank<k>``,
    any other ``events-*.jsonl`` → its stem. Rotated segments belong to
    their base journal (``read_events`` spans them), so they do not
    appear as separate entries."""
    out: dict = {}
    for name in sorted(os.listdir(logdir)):
        path = os.path.join(logdir, name)
        if not os.path.isfile(path):
            continue
        if name == "events.jsonl":
            out["driver"] = path
        elif (m := _RANK_FILE.match(name)):
            out[f"rank{int(m.group(1))}"] = path
        elif name.startswith("events-") and name.endswith(".jsonl"):
            out[name[len("events-") : -len(".jsonl")]] = path
    return out


def _anchor_key(ev: dict):
    """Identity of a gang-wide event across journals: the kind plus its
    stable ordinal fields (restart ordinal, world size, an explicit sync
    id) — wall time deliberately excluded (it is what we are solving
    for). Round 22 adds ``member``: two stall verdicts on different
    members are different instants and must never alias. The per-rank
    auto-tags (``rank``, ``step``) stay OUT of the key — rank journals
    stamp ``rank=`` on every event they record, so keying on either
    would split the driver's copy of a shared anchor from the ranks'
    copies (heartbeats, the stream those tags would disambiguate, are
    excluded from anchoring wholesale via ``_ANCHOR_KINDS``)."""
    return (
        ev.get("kind"),
        ev.get("restart"),
        ev.get("restarts"),
        ev.get("world"),
        ev.get("from_world"),
        ev.get("sync"),
        ev.get("member"),
    )


def estimate_skew(journals: dict) -> dict:
    """Per-journal clock offset (seconds, to SUBTRACT) from shared gang
    anchors. The reference journal is the one holding the most anchor
    events (ties: label order, so ``driver`` wins over ``rank0``); its
    offset is 0 by construction."""
    anchors = {
        label: {
            _anchor_key(e): e["ts"]
            for e in evs
            if e.get("kind") in _ANCHOR_KINDS
            and isinstance(e.get("ts"), (int, float))
        }
        for label, evs in journals.items()
    }
    if not anchors:
        return {}
    ref = min(anchors, key=lambda lb: (-len(anchors[lb]), lb))
    offsets = {}
    for label, own in anchors.items():
        shared = [
            own[k] - anchors[ref][k] for k in own if k in anchors[ref]
        ]
        offsets[label] = (
            float(statistics.median(shared)) if label != ref and shared else 0.0
        )
    return offsets


def merge(source) -> dict:
    """Merge a gang's journals into one fleet timeline.

    ``source`` is a logdir (journals discovered per
    :func:`discover_journals`) or an explicit ``{label: path}`` /
    ``{label: events-list}`` mapping. Returns::

        {"ranks": [label, ...],            # track order: driver first
         "skew_s": {label: offset},
         "events": [...]}                  # ts skew-adjusted, sorted;
                                           # each event carries _src

    The per-event ``_src`` label keys the chrome-trace track and the
    fleet report; the original journals are untouched."""
    if isinstance(source, str):
        paths = discover_journals(source)
        if not paths:
            raise FileNotFoundError(f"no events*.jsonl journals under {source}")
        journals = {lb: read_events(p) for lb, p in paths.items()}
    else:
        journals = {
            lb: (read_events(v) if isinstance(v, str) else list(v))
            for lb, v in source.items()
        }
    skew = estimate_skew(journals)
    ranks = sorted(
        journals,
        key=lambda lb: (lb != "driver", _rank_ordinal(lb), lb),
    )
    merged = []
    for label, evs in journals.items():
        off = skew.get(label, 0.0)
        for ev in evs:
            e = dict(ev)
            e["_src"] = label
            if isinstance(e.get("ts"), (int, float)) and off:
                e["ts"] = e["ts"] - off
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ts") or 0.0))
    return {"ranks": ranks, "skew_s": skew, "events": merged}


def _rank_ordinal(label: str) -> int:
    m = re.match(r"rank(\d+)$", label)
    return int(m.group(1)) if m else 1 << 30


def gang_chrome_trace(merged: dict) -> dict:
    """The fleet timeline in the chrome trace event format: one PROCESS
    track per journal (pid = track index, named via ``process_name``
    metadata), ``span`` events as complete ("X") slices anchored on the
    skew-adjusted WALL clock (a journal's ``ts_us`` is process-local
    perf_counter time and never comparable across ranks — the span's
    journal-event ``ts`` is its close wall time, so start = ts − dur),
    and lifecycle moments as instant ("i") events. Gang-wide kinds
    (:data:`GANG_KINDS`) are mirrored onto EVERY rank track — a gang
    restart IS an event on each rank — plus worker_start / checkpoint /
    rollback / preemption / serving admissions and completions on their
    own rank's track."""
    ranks = merged["ranks"]
    pids = {label: i for i, label in enumerate(ranks)}
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pids[label],
            "tid": 0,
            "args": {"name": label},
        }
        for label in ranks
    ]
    stamped = [
        e for e in merged["events"] if isinstance(e.get("ts"), (int, float))
    ]
    if not stamped:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    t0 = min(e["ts"] for e in stamped)

    def us(ts: float) -> float:
        return (ts - t0) * 1e6

    local_instants = (
        "worker_start",
        "checkpoint_save",
        "checkpoint_restore",
        "rollback",
        "rollback_compiled",
        "preemption",
        "restore",
        "request_submit",
        "admission",
        "completion",
        # Round 19: injected-fault + corrupt-mailbox-recovery events stay
        # PER-RANK instants (never GANG_KINDS — multiple ranks can record
        # the same kind with colliding anchor keys, which would poison
        # estimate_skew's shared-lifecycle-anchor matching).
        "failpoint",
        "mailbox_corrupt",
        # Round 22: a heartbeat belongs to the rank that beat — checked
        # BEFORE the GANG_KINDS mirror below (it is in GANG_KINDS only
        # for the report's last-progress join, never a fleet-wide
        # instant to stamp on every track).
        "heartbeat",
    )
    for ev in stamped:
        kind = ev.get("kind")
        pid = pids.get(ev["_src"], 0)
        args = {
            k: v for k, v in ev.items() if k not in ("_src", "kind", "ts")
        }
        if kind in local_instants:
            events.append(
                {
                    "name": kind,
                    "cat": "lifecycle",
                    "ph": "i",
                    "s": "p",
                    "ts": us(ev["ts"]),
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
        elif kind == "span":
            dur = float(ev.get("dur_us", 0.0))
            events.append(
                {
                    "name": ev.get("name", "?"),
                    "cat": ev.get("cat", "host"),
                    "ph": "X",
                    "ts": us(ev["ts"]) - dur,
                    "dur": dur,
                    "pid": pid,
                    "tid": 0,
                    "args": dict(ev.get("args", {})),
                }
            )
        elif kind in GANG_KINDS:
            for label in ranks:  # the gang moment, visible on every track
                events.append(
                    {
                        "name": kind,
                        "cat": "lifecycle",
                        "ph": "i",
                        "s": "g",
                        "ts": us(ev["ts"]),
                        "pid": pids[label],
                        "tid": 0,
                        "args": args,
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def fleet_summary(merged: dict) -> dict:
    """The ``obs_report --gang`` payload: per-rank event counts and wall
    spans, the estimated skew, and the merged lifecycle history (each
    entry tagged with the journal that recorded it)."""
    from distributed_tensorflow_tpu.observability import format as obs_format

    ts_newest = max(
        (
            e["ts"]
            for e in merged["events"]
            if isinstance(e.get("ts"), (int, float))
        ),
        default=None,
    )
    per_rank: dict = {}
    for label in merged["ranks"]:
        evs = [e for e in merged["events"] if e["_src"] == label]
        ts = [
            e["ts"] for e in evs if isinstance(e.get("ts"), (int, float))
        ]
        kinds: dict = {}
        for e in evs:
            kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
        per_rank[label] = {
            "events": len(evs),
            "kinds": dict(sorted(kinds.items())),
            "wall_span_s": round(max(ts) - min(ts), 3) if ts else 0.0,
        }
        # Round 22 (progress watchdog): last-progress age from the rank's
        # newest heartbeat event, measured against the merged timeline's
        # end — a member whose age keeps growing while the gang's clock
        # advances is stalling, visible here BEFORE the verdict fires.
        beats = [
            e
            for e in evs
            if e.get("kind") == "heartbeat"
            and isinstance(e.get("ts"), (int, float))
        ]
        if beats and ts_newest is not None:
            last = max(beats, key=lambda e: e["ts"])
            per_rank[label]["last_progress"] = {
                "step": last.get("step"),
                "age_s": round(ts_newest - last["ts"], 3),
            }
    # Round 23 (disaggregated fleet): tag each replica's rank row with
    # its role from the router's one-shot fleet_roles event, so the
    # report reads "replica0 [prefill]: ..." without a separate join.
    for ev in merged["events"]:
        if ev.get("kind") == "fleet_roles":
            for name, role in (ev.get("roles") or {}).items():
                if name in per_rank:
                    per_rank[name]["role"] = role
    lifecycle = []
    for ev in merged["events"]:
        kind = ev.get("kind")
        # heartbeat is a per-rank stream (summarized as last_progress
        # above) — listing every beat would drown the lifecycle history.
        if kind == "heartbeat":
            continue
        if kind in GANG_KINDS or kind in (
            "preemption", "rollback", "restore", "weight_swap", "serve_drain",
            "failpoint", "mailbox_corrupt",
        ):
            try:
                line = obs_format.render(kind, ev)[0]
            except KeyError:
                line = f"{kind}: {ev}"
            lifecycle.append(
                {"ts": ev.get("ts"), "src": ev["_src"], "kind": kind,
                 "line": line}
            )
    ts_all = [
        e["ts"]
        for e in merged["events"]
        if isinstance(e.get("ts"), (int, float))
    ]
    return {
        "ranks": per_rank,
        "skew_s": {k: round(v, 6) for k, v in merged["skew_s"].items()},
        "events": len(merged["events"]),
        "wall_span_s": round(max(ts_all) - min(ts_all), 3) if ts_all else 0.0,
        "lifecycle": lifecycle,
        "worker_starts": {
            label: per_rank[label]["kinds"].get("worker_start", 0)
            for label in merged["ranks"]
        },
    }
