"""Live telemetry endpoint: ``/metrics`` (Prometheus text) + ``/healthz``.

The round-10 registry could only be read post-hoc (journal snapshots at
run end); a serving fleet needs the numbers WHILE the process runs — a
scraper polling ``/metrics``, a load balancer polling ``/healthz``. This
is that surface: a stdlib ``http.server`` on a daemon thread, serving

- ``GET /metrics`` — ``MetricsRegistry.prometheus_text()`` at scrape
  time (the registry's instruments are mutated in place by the hot loop,
  so the scrape always sees current values; no push pipeline, no deps);
- ``GET /healthz`` — a JSON liveness document: ``uptime_s``, plus
  whatever the component's ``health_fn`` reports (the TextServer wires
  heartbeat age / slots_busy / queue depth; the elastic driver wires
  world_size / restarts). Responds 200 while the process is up — the
  *content* carries the judgement, mirroring how the gang's heartbeat
  detector separates liveness from progress.

Opt-in by construction: nothing listens unless a component was given a
port (``TextServer(metrics_port=...)``, ``launch_local --metrics-port``).
``port=0`` in the constructor binds an ephemeral port (the bound port is
returned by :meth:`start` — tests use this); the component knobs treat
0/None as "off" so production wiring stays explicit.

jax-free (lean-import convention), stdlib only; the handler thread never
touches jax state — it only reads the registry and calls ``health_fn``,
both plain-Python.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsExporter:
    """Background ``/metrics`` + ``/healthz`` endpoint over one registry.

    ``health_fn() -> dict`` contributes the component-specific half of
    the health document; exceptions inside it degrade to an ``"error"``
    field rather than a dead endpoint (a monitoring surface must not
    take the serving process down — or go dark — because one gauge
    read raced a shutdown)."""

    def __init__(
        self,
        metrics,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        health_fn=None,
    ):
        self.metrics = metrics
        self.health_fn = health_fn
        self._host = host
        self._want_port = int(port)
        self._httpd = None
        self._thread = None
        self._t0 = time.time()

    @property
    def port(self) -> int | None:
        """The bound port (None until :meth:`start`)."""
        return None if self._httpd is None else self._httpd.server_address[1]

    @property
    def url(self) -> str | None:
        return (
            None
            if self._httpd is None
            else f"http://{self._host}:{self.port}"
        )

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port.
        Idempotent (a second start returns the live port)."""
        if self._httpd is not None:
            return self.port
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                if self.path.split("?")[0] == "/metrics":
                    body = exporter.metrics.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/healthz":
                    body = json.dumps(exporter._health()).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stdout
                pass

        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), _Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="dtf-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def _health(self) -> dict:
        doc = {"status": "ok", "uptime_s": round(time.time() - self._t0, 3)}
        if self.health_fn is not None:
            try:
                doc.update(self.health_fn() or {})
            except Exception as exc:  # noqa: BLE001 — see class docstring
                doc["error"] = f"{type(exc).__name__}: {exc}"
        return doc

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
