"""Process-local metrics registry: counters, gauges, histograms.

The quantitative half of the telemetry layer (the journal carries
discrete events; this carries rates and distributions): trainers record
step time, rollbacks, and checkpoint bytes/duration; the elastic gang
records per-worker heartbeat age, restarts, resizes, and world size; the
text server records queue depth, slot occupancy, TTFT, and per-request
latency. Two export surfaces:

- :meth:`MetricsRegistry.prometheus_text` — the Prometheus text
  exposition format, scrapeable as-is;
- :meth:`MetricsRegistry.flush_to` — a ``metrics`` snapshot event into
  the journal, which ``tools/obs_report.py`` folds into the run summary.

Hot-loop discipline: histograms use FIXED bucket edges with
preallocated integer counts (``observe`` is a bisect + two adds — no
allocation, no percentile math on the record path; percentiles are
estimated at READ time from the cumulative buckets). Instruments are
created once (``registry.counter(...)`` at init) and the returned object
is mutated directly in the loop.

jax-free (lean-import convention): stdlib only.
"""

from __future__ import annotations

import math
from bisect import bisect_left

# Default latency edges (seconds): 1 ms → ~2 min, roughly ×2 per bucket —
# wide enough for both a ~100 ms-roundtrip tunnel chip and local CPU runs.
LATENCY_EDGES_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 120.0,
)

# Millisecond edges for step/dispatch times: the whole-epoch Pallas kernel
# sits at µs/step, the tunneled eager loop at ~100 ms/dispatch — both must
# land inside the range, not in overflow.
TIME_MS_EDGES = (
    0.001, 0.01, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 5000.0, 30000.0,
)


def _fmt(v: float) -> str:
    """Prometheus float rendering: integers without the trailing .0."""
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    if f in (math.inf, -math.inf):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


class Counter:
    """Monotone counter."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: dict | None = None):
        self.name = name
        self.labels = dict(labels or {})
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        self.value += n


class Gauge:
    """Set-to-current-value instrument."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: dict | None = None):
        self.name = name
        self.labels = dict(labels or {})
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


class Histogram:
    """Fixed-edge histogram. ``counts[i]`` holds observations ≤
    ``edges[i]`` exclusive of lower buckets; ``counts[-1]`` is the
    overflow (+Inf) bucket. ``observe`` never allocates."""

    __slots__ = ("name", "labels", "edges", "counts", "sum", "count")

    def __init__(
        self, name: str, edges=LATENCY_EDGES_S, labels: dict | None = None
    ):
        edges = tuple(float(e) for e in edges)
        if not edges or list(edges) != sorted(set(edges)):
            raise ValueError(
                f"histogram {name} needs strictly increasing edges, "
                f"got {edges}"
            )
        self.name = name
        self.labels = dict(labels or {})
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.edges, v)] += 1
        self.sum += v
        self.count += 1

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the ``q``-quantile (the usual
        Prometheus-style read: exact enough for SLO eyeballing, cheap
        enough for a report tool). Overflow observations report the top
        edge."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return float("nan")
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                return self.edges[min(i, len(self.edges) - 1)]
        return self.edges[-1]


class MetricsRegistry:
    """Get-or-create instrument registry. One per component (trainer,
    gang, server); ``snapshot``/``prometheus_text``/``flush_to`` read the
    whole family."""

    def __init__(self):
        self._metrics: dict = {}  # (name, label-items) -> instrument

    @staticmethod
    def _key(name: str, labels: dict | None):
        return (name, tuple(sorted((labels or {}).items())))

    def _get(self, cls, name, labels, **kw):
        key = self._key(name, labels)
        inst = self._metrics.get(key)
        if inst is None:
            inst = cls(name, labels=labels, **kw)
            self._metrics[key] = inst
        elif not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}"
            )
        return inst

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(
        self, name: str, edges=LATENCY_EDGES_S, labels: dict | None = None
    ) -> Histogram:
        return self._get(Histogram, name, labels, edges=edges)

    def __iter__(self):
        return iter(self._metrics.values())

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready state of every instrument (the journal's ``metrics``
        event payload; obs_report folds these into the run summary)."""
        out: dict = {}
        for m in self._metrics.values():
            entry: dict = {"labels": m.labels} if m.labels else {}
            if isinstance(m, Histogram):
                entry.update(
                    type="histogram",
                    edges=list(m.edges),
                    counts=list(m.counts),
                    sum=m.sum,
                    count=m.count,
                )
            else:
                entry.update(
                    type="counter" if isinstance(m, Counter) else "gauge",
                    value=m.value,
                )
            out.setdefault(m.name, []).append(entry)
        return out

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (histograms as cumulative
        ``_bucket{le=...}`` series plus ``_sum``/``_count``)."""
        by_name: dict = {}
        for m in self._metrics.values():
            by_name.setdefault(m.name, []).append(m)
        lines: list[str] = []
        for name in sorted(by_name):
            family = by_name[name]
            kind = (
                "histogram"
                if isinstance(family[0], Histogram)
                else "counter" if isinstance(family[0], Counter) else "gauge"
            )
            lines.append(f"# TYPE {name} {kind}")
            for m in family:
                base = self._labelstr(m.labels)
                if isinstance(m, Histogram):
                    cum = 0
                    for edge, c in zip(m.edges, m.counts):
                        cum += c
                        le = self._labelstr({**m.labels, "le": _fmt(edge)})
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = self._labelstr({**m.labels, "le": "+Inf"})
                    lines.append(f"{name}_bucket{le} {m.count}")
                    lines.append(f"{name}_sum{base} {_fmt(m.sum)}")
                    lines.append(f"{name}_count{base} {m.count}")
                else:
                    lines.append(f"{name}{base} {_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _labelstr(labels: dict) -> str:
        if not labels:
            return ""
        inner = ",".join(
            f'{k}="{str(v)}"' for k, v in sorted(labels.items())
        )
        return "{" + inner + "}"

    def flush_to(self, journal, **tags) -> dict:
        """Emit the snapshot as one ``metrics`` journal event."""
        return journal.emit("metrics", metrics=self.snapshot(), **tags)
