"""Unified telemetry layer (round 10): journal, metrics, spans.

Four pieces over the reference's stdout-only instrumentation
(tfdist_between.py:98-110; SURVEY.md §5):

- :mod:`~.journal` — typed append-only JSONL event stream
  (``<logdir>/events.jsonl``), rank/world/run tagged; every structured
  stdout line is rendered FROM one of these events (byte-identical
  output, machine-readable superset).
- :mod:`~.format` — the event→line renderers (the single home of the
  ``Restart:``/``Resize:``/``Rollback:``/… wording; grep-lint-enforced).
- :mod:`~.metrics` — process-local counters/gauges/fixed-edge histograms
  with Prometheus text export and journal snapshots.
- :mod:`~.spans` — chrome-trace host spans whose dispatch flavor refuses
  to close without a D2H value fetch (the honest barrier, CLAUDE.md).

The whole package is jax-free (lean-import convention): it imports and
fully works on a degraded container, like the elastic driver layer it
instruments. Reader tooling: ``tools/obs_report.py``. Docs:
``docs/observability.md``.
"""

from distributed_tensorflow_tpu.observability.format import emit_line, render
from distributed_tensorflow_tpu.observability.journal import (
    EventJournal,
    NullJournal,
    append_event,
    configure,
    emit,
    get_journal,
    read_events,
)
from distributed_tensorflow_tpu.observability.metrics import (
    LATENCY_EDGES_S,
    TIME_MS_EDGES,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from distributed_tensorflow_tpu.observability.spans import (
    DispatchSpan,
    SpanRecorder,
    chrome_trace,
    force_host,
)

__all__ = [
    "EventJournal",
    "NullJournal",
    "append_event",
    "configure",
    "emit",
    "get_journal",
    "read_events",
    "emit_line",
    "render",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "LATENCY_EDGES_S",
    "TIME_MS_EDGES",
    "DispatchSpan",
    "SpanRecorder",
    "chrome_trace",
    "force_host",
]
