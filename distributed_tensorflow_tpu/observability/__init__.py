"""Unified telemetry layer (rounds 10 + 12): journal, metrics, spans,
tracing, gang aggregation, live exporter.

Over the reference's stdout-only instrumentation
(tfdist_between.py:98-110; SURVEY.md §5):

- :mod:`~.journal` — typed append-only JSONL event stream
  (``<logdir>/events.jsonl``), rank/world/run tagged, optional
  size-based rotation; every structured stdout line is rendered FROM one
  of these events (byte-identical output, machine-readable superset).
- :mod:`~.format` — the event→line renderers (the single home of the
  ``Restart:``/``Resize:``/``Rollback:``/… wording; grep-lint-enforced).
- :mod:`~.metrics` — process-local counters/gauges/fixed-edge histograms
  with Prometheus text export and journal snapshots.
- :mod:`~.spans` — chrome-trace host spans whose dispatch flavor refuses
  to close without a D2H value fetch (the honest barrier, CLAUDE.md).
- :mod:`~.tracing` — trace ids joining every event of one logical
  operation (a serving request, a trainer run, a gang incarnation);
  ambient thread-local context auto-tags journal emits.
- :mod:`~.aggregate` — N ranks' journals merged into one fleet timeline
  (skew-aligned on shared gang lifecycle anchors) with a per-rank-track
  chrome trace (``obs_report --gang``).
- :mod:`~.exporter` — live ``/metrics`` (Prometheus) + ``/healthz`` over
  stdlib http, wired into TextServer and the elastic driver.

The whole package is jax-free (lean-import convention): it imports and
fully works on a degraded container, like the elastic driver layer it
instruments. Reader tooling: ``tools/obs_report.py``; perf gate:
``tools/regression_gate.py``. Docs: ``docs/observability.md``.
"""

from distributed_tensorflow_tpu.observability import aggregate, tracing
from distributed_tensorflow_tpu.observability.exporter import MetricsExporter
from distributed_tensorflow_tpu.observability.format import emit_line, render
from distributed_tensorflow_tpu.observability.journal import (
    EventJournal,
    NullJournal,
    append_event,
    configure,
    configure_from_env,
    emit,
    get_journal,
    journal_segments,
    rank_journal_path,
    read_events,
)
from distributed_tensorflow_tpu.observability.metrics import (
    LATENCY_EDGES_S,
    TIME_MS_EDGES,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from distributed_tensorflow_tpu.observability.spans import (
    DispatchSpan,
    SpanRecorder,
    chrome_trace,
    force_host,
)

__all__ = [
    "EventJournal",
    "NullJournal",
    "MetricsExporter",
    "aggregate",
    "append_event",
    "configure",
    "configure_from_env",
    "emit",
    "get_journal",
    "journal_segments",
    "rank_journal_path",
    "read_events",
    "tracing",
    "emit_line",
    "render",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "LATENCY_EDGES_S",
    "TIME_MS_EDGES",
    "DispatchSpan",
    "SpanRecorder",
    "chrome_trace",
    "force_host",
]
