"""Host-side trace spans: chrome-trace/Perfetto-loadable, barrier-honest.

``jax.profiler`` (utils/profiler.py) answers "what did the DEVICE do";
these spans answer "what did the HOST wait for" — dispatch→fetch windows,
compiles, checkpoint save/restore, serving prefill/decode chunks — in the
chrome trace event format, so one ``obs_report --trace`` export loads in
Perfetto/chrome://tracing next to a device trace.

The API bakes in the repo's hard-won timing discipline (CLAUDE.md TIMING
TRAP): through the tunneled chip, ``jax.block_until_ready`` returns
optimistically, so the only trustworthy end-of-execution barrier is a
device-to-host VALUE fetch. A :meth:`SpanRecorder.dispatch` span therefore
**refuses to close** until :meth:`~DispatchSpan.fetch` has materialized a
value on the host — timing a dispatch without the fetch raises instead of
silently recording enqueue time (the class of bug that cost rounds 1-4
three separate debugging cycles). Generic host work (compile, file I/O)
uses :meth:`SpanRecorder.span`, which has no such requirement.

jax-free (lean-import convention): the fetch coerces via ``__array__`` /
``float`` — a jax array's ``__array__`` IS the D2H copy, and numpy is
imported lazily only when an array-likes is fetched.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time


def force_host(value):
    """Materialize ``value`` on the host — the trustworthy execution
    barrier. Device arrays come back as numpy (``__array__`` performs the
    D2H copy); Python/0-d scalars coerce through ``float``. ``None`` is
    refused: a dispatch that produced nothing fetchable has nothing to
    prove it ran."""
    if value is None:
        raise ValueError(
            "dispatch fetch needs a value produced by the dispatch "
            "(device array or scalar); got None"
        )
    if hasattr(value, "__array__"):
        import numpy as np

        return np.asarray(value)
    if isinstance(value, (list, tuple)):
        return type(value)(force_host(v) for v in value)
    return float(value)


class DispatchSpan:
    """An open dispatch span. ``fetch(value)`` is the only way to close
    it cleanly: it performs the D2H materialization and stamps the span's
    end time AT the fetch — the honest dispatch+execute window."""

    def __init__(self, recorder: "SpanRecorder", name: str, args: dict):
        self._rec = recorder
        self.name = name
        self.args = args
        self._t0 = recorder._now()
        self._t_fetch = None

    def fetch(self, value):
        host = force_host(value)
        self._t_fetch = self._rec._now()
        return host

    @property
    def fetched(self) -> bool:
        return self._t_fetch is not None


class SpanRecorder:
    """In-memory span sink with chrome-trace export and optional journal
    mirroring (each closed span also lands as a ``span`` event, so
    ``obs_report`` can rebuild the trace from ``events.jsonl`` alone).
    Keeps at most ``max_spans`` (oldest dropped, ``dropped`` counts them)
    so a long-lived server cannot grow without bound."""

    def __init__(self, journal=None, *, max_spans: int = 100_000):
        self.journal = journal
        self.max_spans = int(max_spans)
        # deque(maxlen=...): O(1) eviction — a list's front-delete would
        # memmove the whole buffer per span once a long-lived server
        # reaches the cap.
        self.spans: collections.deque = collections.deque(maxlen=self.max_spans)
        self.dropped = 0
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    def _now(self) -> float:
        return time.perf_counter() - self._perf0

    def _record(
        self, name: str, cat: str, t0: float, t1: float, args: dict
    ) -> dict:
        span = {
            "name": name,
            "cat": cat,
            "ts_us": t0 * 1e6,
            "dur_us": max(t1 - t0, 0.0) * 1e6,
            "wall_ts": self._wall0 + t0,
            "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            span["args"] = dict(args)
        if len(self.spans) == self.max_spans:
            self.dropped += 1  # deque maxlen evicts the oldest on append
        self.spans.append(span)
        if self.journal is not None:
            self.journal.emit(
                "span",
                name=name,
                cat=cat,
                ts_us=span["ts_us"],
                dur_us=span["dur_us"],
                **({"args": span["args"]} if args else {}),
            )
        return span

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """Generic host span (compile, checkpoint I/O, scheduler work)."""
        t0 = self._now()
        try:
            yield
        finally:
            self._record(name, cat, t0, self._now(), args)

    @contextlib.contextmanager
    def dispatch(self, name: str, **args):
        """A device-dispatch span. The body MUST call ``fetch(value)`` on
        something the dispatch produced; exiting without it raises
        RuntimeError — per CLAUDE.md's timing traps, a dispatch span
        without a D2H fetch would time enqueue, not execution. The span's
        end is the fetch completion time."""
        sp = DispatchSpan(self, name, args)
        try:
            yield sp
        except BaseException:
            # The dispatch died: record what we know, never mask the error.
            self._record(
                name, "dispatch", sp._t0, self._now(),
                {**args, "error": True},
            )
            raise
        if not sp.fetched:
            raise RuntimeError(
                f"dispatch span {name!r} closed without a D2H fetch: call "
                "span.fetch(<value the dispatch produced>) before exiting "
                "— through the device link, timing without a value fetch "
                "measures enqueue, not execution (CLAUDE.md TIMING TRAP)"
            )
        self._record(
            name, "dispatch", sp._t0, sp._t_fetch, {**args, "barrier": "d2h"}
        )

    def mark(self) -> float:
        """A start-of-dispatch timestamp for :meth:`dispatch_fetch` —
        take it immediately before issuing the dispatch."""
        return self._now()

    def dispatch_fetch(self, name: str, value, *, start: float | None = None,
                       **args):
        """One-call dispatch span for straight-line code: materializes
        ``value`` on the host (the D2H barrier — this call CANNOT record
        without fetching, same honesty guarantee as :meth:`dispatch`) and
        records the span from ``start`` (a :meth:`mark` taken before the
        dispatch; default: now, i.e. fetch-wait only). Returns the host
        value, so it drops in where ``jax.device_get`` was."""
        t0 = self._now() if start is None else float(start)
        host = force_host(value)
        self._record(
            name, "dispatch", t0, self._now(), {**args, "barrier": "d2h"}
        )
        return host

    # -- export ------------------------------------------------------------

    def chrome_trace(self) -> dict:
        return chrome_trace(self.spans)

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f)
        return path


def chrome_trace(spans) -> dict:
    """Span dicts (recorder-shaped OR ``span`` journal events) → the
    chrome trace event format Perfetto loads. Complete ("X") events with
    microsecond ts/dur, one process, tids preserved when present."""
    pid = os.getpid()
    events = []
    for s in spans:
        events.append(
            {
                "name": s.get("name", "?"),
                "cat": s.get("cat", "host"),
                "ph": "X",
                "ts": float(s.get("ts_us", 0.0)),
                "dur": float(s.get("dur_us", 0.0)),
                "pid": int(s.get("pid", pid)),
                "tid": int(s.get("tid", 0)),
                "args": dict(s.get("args", {})),
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
