"""Serving fleet: a health-checked replica router with zero-loss failover.

The reference's availability story was training-side only (a restarted
worker re-attached to live PS state, reference tfdist_between.py:83);
rounds 6-14 rebuilt and surpassed it for training (durable checkpoints,
elastic gang resize, DiLoCo through failures). Serving — the surface the
north-star's "millions of users" actually touch — was still ONE Python
loop: a dead TextServer lost every resident request. This module is the
serving twin of that machinery, grounded in the paper's async-beats-sync
thesis: replicas fail and recover INDEPENDENTLY while the fleet keeps
serving, exactly as the reference's async PS workers did for training —
serving replicas share no collectives, so nothing gang-restarts.

Topology
--------
A :class:`ReplicaRouter` supervises N serving replicas. Each replica is a
:class:`ReplicaHandle` bundling the round-7 elastic primitives
(train/elastic.py — the reuse is deliberate, one supervision vocabulary
for training and serving):

- an ``ElasticAgent`` (spawn / poll the exit code / kill) over the
  replica process — ``run_replica`` below, a TextServer restored from
  ``checkpoint_dir`` driving submit/step/result against a filesystem
  mailbox;
- an ``HttpHealth`` probe over the replica's ``/healthz``
  (observability/exporter.py): dead / stalled verdicts mirror the
  heartbeat detector's, and the last good document carries the ROUTING
  signals (``queue_saturation``, ``slots_busy``, ``draining``);
- a :class:`MailboxClient`: requests in, results out, every file written
  atomically (tmp + ``os.replace``). The mailbox OUTLIVES the process —
  results a replica committed before dying are still collected, and
  anything without a result re-admits elsewhere.

Zero-loss failover
------------------
The router keeps the AUTHORITATIVE request table: every request carries
its trace id and full generation config end-to-end, so when a replica
dies (exit code, dead, or stalled verdict) its uncollected in-flight
requests are re-admitted to a healthy replica and re-served FROM SCRATCH.
Continuous batching makes chunk-boundary re-admission safe, and the
round-9 parity contract (greedy and seeded-sampling streams are
deterministic functions of prompt + config) makes the retried stream
token-identical — the client observes a latency blip, never a changed or
lost stream. Duplicate results (a slow replica finishing after its work
was re-served) deduplicate on the trace id: first terminal result wins.
A request the deadline cancelled is terminal — retries never resurrect
it (``request_cancelled`` is the record).

Failed replicas relaunch under a restart budget with jittered backoff
(``resilience.backoff_delay`` — the gang's own formula; members restart
independently, so there is no single retry() call to wrap). A replica
over budget is BENCHED; when the non-benched roster would fall below
``min_replicas`` the router fail-stops (:class:`FleetBelowFloor`, the
serving analog of ``GangBelowFloor`` — unserved requests stay with the
caller, nothing durable is lost).

Routing is prefix-cache-aware: same-prefix sessions stick to the replica
holding the warm radix (first ``affinity_tokens`` tokens key a sticky
map), spilling to the least-loaded replica when the sticky target is
saturated (``/healthz`` ``queue_saturation`` ≥ ``spill_threshold``) —
backed by TextServer's bounded admission queue, which rejects loudly
instead of growing without bound.

Live weight swap
----------------
``ReplicaRouter.swap_weights()`` sends each replica a swap control; the
replica adopts the newest CRC-verified checkpoint between chunk
boundaries (``TextServer.swap_from_checkpoint``: admission pauses, the
last old-weight resident finishes, the param tree is replaced — params
are runtime args of every compiled graph, so NOTHING recompiles) —
closing the DiLoCo train→publish→serve loop. Residents admitted before
the swap complete under the old weights' parity contract; new admissions
serve the new weights; no request is dropped.

Overload robustness (round 21)
------------------------------
Under load the router degrades gracefully instead of rejecting blindly
(docs/serving.md §overload). Requests carry ``priority`` + ``deadline_s``
fleet-wide: queued requests live in PER-CLASS queues served weighted-fair
(deficit round robin, weight ``priority+1`` — low classes still progress,
high classes get the larger share), earliest-deadline-first within a
class; a queued request past its deadline — or provably unable to finish
inside it (remaining budget x the fleet's measured per-token EWMA) — is
SHED before a route is spent on it (:class:`~serve_pool.RequestShed`
terminal result, ``request_shed`` journal event; distinct from a
``RequestCancelled`` resident). A per-replica CIRCUIT BREAKER watches
route timeouts (``route_timeout_s``; default None = off): consecutive
failures open it and divert routes immediately — BEFORE the slower
HttpHealth verdict lands — half-open admits one probe after
``breaker_reset_s``, any collected result closes it. Breaker transitions
are routing decisions: they emit ``breaker_*`` journal events and charge
NOTHING to the restart budget (supervision still owns kill/relaunch).
Default path (no priority/deadline, no route timeout) is byte-identical
to round 16.

Out of scope (deliberately): sharded (tensor-parallel) serving and the
HTTP/SSE streaming frontend — both gate on the partition-rule engine
(ROADMAP item 2) and deserve their own PR.

jax-free at import (the lean-import convention): the router runs on a
driver host with no accelerator stack; only ``run_replica`` (the spawned
worker) imports the engine. Proofs: tests/test_serve_fleet.py pins the
router state machine on a fake replica table (the test_elastic.py
pattern); tests/integration/test_serve_fleet_failover.py SIGKILLs a
replica of a live ≥3-replica fleet mid-decode and asserts zero failed
requests + token-identical streams (RUN_SLOW). docs/serving.md §fleet.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import subprocess
import sys
import time
from collections import deque
from typing import Sequence

from distributed_tensorflow_tpu.observability import journal as obs_journal
from distributed_tensorflow_tpu.observability import tracing
from distributed_tensorflow_tpu.observability.metrics import MetricsRegistry
from distributed_tensorflow_tpu.serve_pool import RequestCancelled, RequestShed
from distributed_tensorflow_tpu.train import failpoints, resilience
from distributed_tensorflow_tpu.train.elastic import (
    ElasticAgent,
    HttpHealth,
    WorkerFailure,
)
from distributed_tensorflow_tpu.utils.summary import lifecycle_event


# GenerationConfig's field names, mirrored here so the jax-free router
# can refuse a malformed config at submit time instead of shipping it to
# a replica whose constructor would die on it (tests/test_serve_fleet.py
# pins the mirror against the real dataclass).
CONFIG_KEYS = ("max_new", "greedy", "temperature", "top_p", "seed", "eos_id")


class FleetBelowFloor(WorkerFailure):
    """Fewer than ``min_replicas`` non-benched replicas remain: the
    router fail-stops (the serving analog of ``GangBelowFloor``) rather
    than pretend a one-replica rump is the fleet the operator asked for."""


# ---------------------------------------------------------------------------
# Filesystem mailbox: the router<->replica transport.
# ---------------------------------------------------------------------------


# The one atomic-JSON primitive (checkpoint manifests, layout sidecars,
# and this mailbox all share it): tmp + os.replace, so a reader never
# sees a torn file and a writer killed mid-write leaves only a ``.tmp``
# that readers skip.
write_json_atomic = resilience.write_json_atomic


def _payload_crc(obj: dict) -> int:
    """CRC32C envelope over the canonical JSON bytes of a mailbox
    payload (sort_keys — writer and reader must agree byte-for-byte).
    Round-6 kernel: native fast path, table fallback, bit-identical."""
    return resilience._crc32c_bytes(
        json.dumps(obj, sort_keys=True).encode("utf-8")
    )


def _read_dir(dirpath: str, on_corrupt=None) -> list[dict]:
    """Read-and-remove every committed JSON file in ``dirpath``, oldest
    first (filenames carry a zero-padded sequence).

    Integrity (round 19): payloads carry a ``_crc`` envelope
    (:func:`_payload_crc`, popped before delivery); a committed file
    that fails the CRC or will not parse is QUARANTINED — removed,
    never delivered, surfaced via ``on_corrupt(name, reason)`` — so
    corrupt bytes cannot poison the router/replica AND cannot be
    re-read forever (the pre-round-19 behavior left unparseable files
    in place for every subsequent poll). Payloads without ``_crc``
    (older writers) deliver unchecked. Transient OSError on open skips
    WITHOUT removing — a racing writer's commit lands by next poll."""
    out = []
    failpoints.fire("fleet.read")
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue  # .tmp.* in flight
        path = os.path.join(dirpath, name)
        try:
            with open(path, encoding="utf-8") as f:
                obj = json.load(f)
        except OSError:  # pragma: no cover — racing writer
            continue
        except ValueError:
            _quarantine(path, name, "json", on_corrupt)
            continue
        crc = obj.pop("_crc", None) if isinstance(obj, dict) else None
        if crc is not None and crc != _payload_crc(obj):
            _quarantine(path, name, "crc", on_corrupt)
            continue
        try:
            os.remove(path)
        except OSError:  # pragma: no cover — racing reader took it
            continue
        out.append(obj)
    return out


def _quarantine(path: str, name: str, reason: str, on_corrupt) -> None:
    try:
        os.remove(path)
    except OSError:  # pragma: no cover
        pass
    if on_corrupt is not None:
        on_corrupt(name, reason)


class MailboxClient:
    """One replica's mailbox: ``<root>/inbox`` (router → replica:
    requests and control messages, one FIFO stream) and ``<root>/outbox``
    (replica → router: results). Both sides write atomically; the
    directories outlive the replica process — that persistence is the
    storage half of the zero-loss contract (committed results survive a
    crash; everything else visibly lacks a result and re-admits).

    Round 19: every write carries a ``_crc`` envelope verified (and
    popped) on read; corrupt committed files are quarantined — removed,
    never delivered, counted in ``corrupt_files`` and journaled as
    ``mailbox_corrupt`` (the router wires its journal in; standalone
    clients ride the process default). Stale ``.tmp`` orphans from
    writers killed mid-write are age-guard swept at construction and on
    ``clear_inbox`` (:func:`resilience.sweep_tmp_orphans` — the age
    guard keeps a live writer's in-flight tmp safe). Failpoints:
    ``fleet.submit``/``fleet.result`` (entry + tear of the committed
    file), ``fleet.read`` at every poll."""

    def __init__(
        self,
        root: str,
        *,
        journal=None,
        metrics=None,
        orphan_age_s: float = 60.0,
    ):
        self.root = root
        self.inbox = os.path.join(root, "inbox")
        self.outbox = os.path.join(root, "outbox")
        os.makedirs(self.inbox, exist_ok=True)
        os.makedirs(self.outbox, exist_ok=True)
        self._seq = 0
        self.journal = journal
        self.metrics = metrics  # round 21: counters beside the journal
        self.orphan_age_s = float(orphan_age_s)
        self.corrupt_files = 0  # quarantined corrupt mailbox files
        for d in (self.inbox, self.outbox):
            resilience.sweep_tmp_orphans(d, age_s=self.orphan_age_s)

    def _next(self, dirpath: str, tag: str) -> str:
        self._seq += 1
        return os.path.join(dirpath, f"{self._seq:08d}-{tag}.json")

    def _write(self, path: str, payload: dict) -> str:
        body = dict(payload)
        body["_crc"] = _payload_crc(payload)
        write_json_atomic(path, body)
        return path

    def _on_corrupt(self, box: str):
        def cb(name: str, reason: str) -> None:
            self.corrupt_files += 1
            if self.metrics is not None:
                self.metrics.counter("mailbox_corrupt_files_total").inc()
            j = self.journal
            if j is None:
                j = obs_journal.get_journal()
            j.emit(
                "mailbox_corrupt",
                mailbox="fleet",
                box=box,
                file=name,
                reason=reason,
                action="quarantined",
            )

        return cb

    # -- router side -------------------------------------------------------

    def submit(self, payload: dict) -> None:
        failpoints.fire("fleet.submit")
        path = self._write(
            self._next(self.inbox, payload.get("trace", "req")), payload
        )
        failpoints.tear("fleet.submit", path)

    def control(self, payload: dict) -> None:
        """Control messages ride the same FIFO stream as requests, so a
        swap lands AFTER everything routed before it."""
        self._write(
            self._next(self.inbox, f"ctl-{payload.get('control')}"), payload
        )

    def poll_results(self) -> list[dict]:
        return _read_dir(self.outbox, self._on_corrupt("outbox"))

    def clear_inbox(self) -> None:
        """Drop undelivered requests (before relaunching a replica: the
        router re-routes its in-flight itself; a fresh incarnation must
        not re-serve work that already failed over elsewhere)."""
        for name in os.listdir(self.inbox):
            try:
                os.remove(os.path.join(self.inbox, name))
            except OSError:  # pragma: no cover
                pass
        resilience.sweep_tmp_orphans(self.inbox, age_s=self.orphan_age_s)

    # -- replica side ------------------------------------------------------

    def take_inbox(self) -> list[dict]:
        return _read_dir(self.inbox, self._on_corrupt("inbox"))

    def put_result(self, payload: dict) -> None:
        failpoints.fire("fleet.result")
        path = self._write(
            self._next(self.outbox, payload.get("trace", "res")), payload
        )
        failpoints.tear("fleet.result", path)


def _np_dtype(name: str):
    """Resolve a dtype name, reaching into ml_dtypes for the storage
    dtypes numpy alone does not know (fp8 variants, bfloat16)."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class MigrationStore:
    """Shared directory of KV-migration posts (round 23,
    docs/serving.md §disaggregation): one CRC-enveloped npz file per
    prefill→decode handoff. File layout: a canonical-JSON header line
    ``{"meta":…, "tokens":…, "trace":…, "crc":…, "nbytes":…}`` followed
    by the raw npz bytes of the KV-block arrays — the CRC covers the npz
    body, so a torn write (truncated past the atomic commit by the
    ``fleet.migrate`` failpoint, or real storage rot) is detected at
    LOAD and quarantined once: removed, counted in ``corrupt_files``,
    journaled as ``mailbox_corrupt`` with ``mailbox="migrate"`` —
    never delivered and never re-read forever (the round-19 discipline).
    The importer does NOT delete a loaded post: the ROUTER owns the
    file's lifetime (removed when the request is terminal), so a decode
    replica dying mid-stream re-imports the same post on failover.

    jax-free; numpy is imported lazily (the router constructs the store
    but only replica workers move arrays through it)."""

    def __init__(self, root: str, *, journal=None, metrics=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.journal = journal
        self.metrics = metrics
        self.corrupt_files = 0
        resilience.sweep_tmp_orphans(root, age_s=60.0)

    def path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def post(self, name: str, payload: dict) -> str:
        """Commit one migration post atomically (tmp + ``os.replace``).
        ``payload`` is a ``TextServer.take_export`` dict: ``arrays``
        (name → ndarray), ``meta``, ``tokens``, ``trace``. Raises
        OSError (incl. FailpointError) on failure — the caller falls
        back to migration-less handoff, never loses the request."""
        import io

        import numpy as np

        failpoints.fire("fleet.migrate")
        arrays: dict = {}
        exotic: dict = {}
        for k, v in payload["arrays"].items():
            a = np.asarray(v)
            if a.dtype.kind == "V":
                # ml_dtypes storage dtypes (fp8/bf16) do not survive
                # np.savez (they load back as opaque void) — ship the
                # raw bytes as uint8 and rebuild from the header's
                # dtype+shape at load (the round-17 mailbox discipline).
                exotic[k] = {"dtype": a.dtype.name, "shape": list(a.shape)}
                a = np.frombuffer(a.tobytes(), np.uint8)
            arrays[k] = a
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        body = buf.getvalue()
        head = {
            "meta": payload["meta"],
            "tokens": [int(t) for t in payload["tokens"]],
            "trace": payload.get("trace"),
            "crc": resilience._crc32c_bytes(body),
            "nbytes": len(body),
        }
        if exotic:
            head["exotic"] = exotic
        path = self.path(name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(json.dumps(head, sort_keys=True).encode("utf-8"))
            f.write(b"\n")
            f.write(body)
        os.replace(tmp, path)
        failpoints.tear("fleet.migrate", path)
        return name

    def load(self, name: str) -> dict | None:
        """Read + verify one post. Returns the payload dict (arrays
        rehydrated), or None when the file is missing (already cleaned
        up) OR corrupt — corrupt commits are quarantined once, and the
        caller's contract is the same either way: fall back to
        re-prefill from the tokens+config that travel with the request
        (zero loss, round-19 stance)."""
        import io

        import numpy as np

        path = self.path(name)
        try:
            with open(path, "rb") as f:
                header = f.readline()
                body = f.read()
        except OSError:
            return None
        try:
            head = json.loads(header)
            if len(body) != int(head["nbytes"]) or (
                resilience._crc32c_bytes(body) != head["crc"]
            ):
                raise ValueError("crc/size mismatch")
            with np.load(io.BytesIO(body)) as z:
                arrays = {k: z[k] for k in z.files}
            for k, spec in (head.get("exotic") or {}).items():
                arrays[k] = np.frombuffer(
                    arrays[k].tobytes(), _np_dtype(spec["dtype"])
                ).reshape(spec["shape"])
        except (ValueError, KeyError, TypeError) as exc:
            self._quarantine(name, path, f"{type(exc).__name__}")
            return None
        return {
            "arrays": arrays,
            "meta": head["meta"],
            "tokens": head["tokens"],
            "trace": head.get("trace"),
        }

    def remove(self, name: str) -> None:
        try:
            os.remove(self.path(name))
        except OSError:
            pass

    def _quarantine(self, name: str, path: str, reason: str) -> None:
        try:
            os.remove(path)
        except OSError:  # pragma: no cover
            pass
        self.corrupt_files += 1
        if self.metrics is not None:
            self.metrics.counter("mailbox_corrupt_files_total").inc()
        j = self.journal if self.journal is not None else (
            obs_journal.get_journal()
        )
        j.emit(
            "mailbox_corrupt",
            mailbox="migrate",
            box="migrate",
            file=name,
            reason=reason,
            action="quarantined",
        )


# ---------------------------------------------------------------------------
# The router.
# ---------------------------------------------------------------------------


class _FleetRequest:
    __slots__ = (
        "rid", "trace", "tokens", "config", "deadline", "deadline_s",
        "t_submit", "replica", "attempts", "done", "cancelled", "failed",
        "shed", "priority", "out", "t_done", "t_routed",
        "leg", "resume_post", "prefill_replica", "leg1_tokens",
    )

    def __init__(self, rid, trace, tokens, config, deadline, deadline_s,
                 now, priority=0):
        self.rid = rid
        self.trace = trace
        self.tokens = tokens
        self.config = config
        self.deadline = deadline  # absolute, router clock; None = none
        self.deadline_s = deadline_s
        self.t_submit = now
        self.replica: str | None = None
        self.attempts = 0  # times (re)routed
        self.done = False
        self.cancelled = False
        self.failed: str | None = None  # terminal rejection (error text)
        self.shed = False  # dropped before any route/prefill (round 21)
        self.priority = priority  # int >= 0; higher = more important
        self.out: list[int] | None = None
        self.t_done: float | None = None
        self.t_routed: float | None = None  # last route, breaker timeout
        # Disaggregated two-leg lifecycle (round 23): "single" in a
        # homogeneous fleet (byte-identical round-21 path); a role fleet
        # routes leg "prefill" first, then — after the prefill replica's
        # migrated result — leg "decode" with the migration post.
        self.leg = "single"
        self.resume_post: str | None = None  # migration post filename
        self.prefill_replica: str | None = None
        self.leg1_tokens: list[int] | None = None

    @property
    def terminal(self) -> bool:
        return (
            self.done or self.cancelled or self.shed
            or self.failed is not None
        )


class ReplicaHandle:
    """One replica under router supervision: the elastic agent (process
    lifecycle), the mailbox client (transport), the /healthz probe
    (verdicts + routing signals), and the router-side supervision state —
    ``starting`` (spawned, health not yet confirmed), ``up``, ``backoff``
    (dead, relaunch scheduled), ``benched`` (restart budget exhausted).
    ``agent``/``health`` are optional so the fast-tier tests drive the
    whole state machine with fakes (the test_elastic.py pattern)."""

    def __init__(
        self,
        name: str,
        *,
        client,
        agent: ElasticAgent | None = None,
        health: HttpHealth | None = None,
        role: str = "both",
    ):
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"unknown replica role {role!r}; prefill|decode|both"
            )
        self.name = name
        self.client = client
        self.agent = agent
        self.health = health
        # Round-23 disaggregation: which leg(s) this replica serves.
        # "both" everywhere = the homogeneous fleet, bitwise round 21.
        self.role = role
        self.state = "starting"
        self.attempts = 0  # restarts charged
        self.relaunch_at: float | None = None
        self.backoff_s = 0.0
        self.inflight: dict[str, _FleetRequest] = {}
        self.cooldown_until = 0.0  # QueueFull backpressure hold-off
        self._next_probe = 0.0
        # Round-21 circuit breaker (routing layer, independent of the
        # supervision states above): closed / open / half_open.
        self.breaker = "closed"
        self.breaker_failures = 0  # consecutive route failures
        self.breaker_until = 0.0  # open -> half_open at this clock
        self.breaker_probe: str | None = None  # the half-open probe trace

    def breaker_reset(self) -> None:
        self.breaker = "closed"
        self.breaker_failures = 0
        self.breaker_until = 0.0
        self.breaker_probe = None

    @property
    def can_prefill(self) -> bool:
        return self.role in ("prefill", "both")

    @property
    def can_decode(self) -> bool:
        return self.role in ("decode", "both")

    @property
    def routable(self) -> bool:
        if self.state != "up":
            return False
        if self.breaker == "open":
            return False
        if self.breaker == "half_open" and self.breaker_probe is not None:
            return False  # one probe at a time
        doc = self.health.last if self.health is not None else None
        return not (doc and doc.get("draining"))


class ReplicaRouter:
    """N serving replicas behind one submit/result surface (module
    docstring for the full contract). Drive with :meth:`step` ticks (or
    :meth:`run_until_done`); ``clock``/``sleep``/``rng`` are injectable
    so the fast-tier tests run the state machine without wall time,
    processes, or sockets."""

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        *,
        min_replicas: int = 1,
        max_restarts: int = 2,
        backoff: float = 1.0,
        max_backoff: float = 30.0,
        jitter: float = 0.25,
        affinity_tokens: int = 16,
        affinity_cap: int = 4096,
        spill_threshold: float = 0.75,
        max_reroutes: int = 8,
        breaker_failures: int = 3,
        breaker_reset_s: float = 5.0,
        route_timeout_s: float | None = None,
        migrate_dir: str | None = None,
        prefix_block_tokens: int = 16,
        migrate_threshold: int | None = None,
        probe_interval_s: float = 0.5,
        poll_interval: float = 0.05,
        journal=None,
        metrics: MetricsRegistry | None = None,
        print_fn=print,
        clock=time.monotonic,
        sleep=time.sleep,
        rng=None,
    ):
        self.replicas = {h.name: h for h in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica names must be unique")
        # Mailbox corruption events (round 19) ride the router's journal
        # unless a client already has its own (fakes lack the attr).
        for h in replicas:
            client = getattr(h, "client", None)
            if (
                client is not None
                and hasattr(client, "journal")
                and client.journal is None
                and journal is not None
            ):
                client.journal = journal
        self.min_replicas = int(min_replicas)
        if not 1 <= self.min_replicas <= len(replicas):
            raise ValueError(
                f"min_replicas must be in [1, {len(replicas)}], got "
                f"{min_replicas}"
            )
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.affinity_tokens = int(affinity_tokens)
        self.affinity_cap = int(affinity_cap)
        self.spill_threshold = float(spill_threshold)
        self.max_reroutes = int(max_reroutes)
        self.breaker_failures = int(breaker_failures)
        self.breaker_reset_s = float(breaker_reset_s)
        # None (the default) disarms route-timeout detection entirely —
        # the round-16 path, byte-identical.
        self.route_timeout_s = (
            None if route_timeout_s is None else float(route_timeout_s)
        )
        self.probe_interval_s = float(probe_interval_s)
        self.poll_interval = float(poll_interval)
        self.journal = (
            journal if journal is not None else obs_journal.get_journal()
        )
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # Round-21 satellite: mailbox corruption counters ride the
        # router's registry (mailbox_corrupt_files_total) so dashboards
        # see rot, not a "silent replica" (docs/known_issues.md).
        for h in replicas:
            client = getattr(h, "client", None)
            if (
                client is not None
                and hasattr(client, "metrics")
                and client.metrics is None
            ):
                client.metrics = self.metrics
        self.print_fn = print_fn
        self.clock = clock
        self.sleep = sleep
        self.rng = rng
        # Per-priority-class queues (round 21). All-default traffic lives
        # in class 0 and dequeues exactly like the old single FIFO deque:
        # rids are monotone and every requeue is an appendleft of rids
        # lower than anything behind them, so FIFO order IS rid order and
        # the EDF key (deadline-or-inf, rid) degenerates to the head.
        self._queues: dict[int, deque[_FleetRequest]] = {}
        self._drr: dict[int, float] = {}  # deficit round-robin credits
        # Fleet per-token seconds (EWMA over completed requests): the
        # router-side "provably cannot finish" shed predicate's only
        # evidence. None until the first completion — the router never
        # sheds on a guess, only on expiry, before then.
        self._tok_ewma: float | None = None
        self._by_rid: dict[int, _FleetRequest] = {}
        self._by_trace: dict[str, _FleetRequest] = {}
        self._affinity: dict[tuple, str] = {}
        self._next_rid = 0
        self._started = False
        self._draining = False
        # Round-23 disaggregation: the two-leg lifecycle arms only when
        # a role-specialized replica exists — an all-"both" fleet keeps
        # the round-21 single-leg path (and its sticky affinity map)
        # bitwise. In a role fleet the sticky map is PROMOTED to a
        # fleet-wide radix-prefix index: routing sees which replica
        # holds which warm prefix (beliefs registered at route time,
        # dropped on death/relaunch/swap) before choosing the prefill
        # leg.
        self._two_leg = any(h.role != "both" for h in replicas)
        # Length-threshold routing (the DistServe-style policy knob):
        # prompts SHORTER than ``migrate_threshold`` tokens skip the
        # two-leg path and serve whole on a decode-capable replica —
        # the handoff only pays for itself when the prefill is long
        # enough to stall a decode batch. None (default) sends every
        # first leg through the prefill pool (the round-23 base path;
        # an all-"both" fleet ignores the knob entirely).
        self.migrate_threshold = (
            None if migrate_threshold is None else int(migrate_threshold)
        )
        self._migrate = (
            MigrationStore(migrate_dir, journal=self.journal,
                           metrics=self.metrics)
            if migrate_dir is not None
            else None
        )
        self._prefix_index = None
        if self._two_leg:
            from distributed_tensorflow_tpu.serve_pool import (
                FleetPrefixIndex,
            )

            self._prefix_index = FleetPrefixIndex(
                block_size=int(prefix_block_tokens)
            )
            self.journal.emit(
                "fleet_roles",
                roles={h.name: h.role for h in replicas},
                migrate_dir=migrate_dir,
            )
        # The checkpoint directory the fleet currently serves when a
        # swap ever pointed it AWAY from the replicas' spawn-time
        # default; re-sent to every replica as it comes (back) up, so a
        # relaunch cannot quietly revert to stale weights. Same-dir
        # swaps need none of this: a restarting replica restores the
        # newest CRC-verified step of its own directory anyway.
        self.current_checkpoint_dir: str | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Spawn every replica (no-op for externally-managed handles)."""
        if self._started:
            return
        self._started = True
        for h in self.replicas.values():
            if h.agent is not None:
                h.agent.start()
            if h.health is None:
                h.state = "up"  # nothing to confirm: trust the spawn
        self.metrics.gauge("replicas_total").set(len(self.replicas))

    def submit(
        self, tokens, config=None, *, deadline_s=None, priority: int = 0
    ) -> int:
        """Queue one request fleet-wide. ``config`` is a GenerationConfig
        dataclass or a plain dict of its fields (the router is jax-free
        and never imports the engine); the FULL config travels with the
        request so a failover re-serves the identical stream. Returns a
        router-scope request id for :meth:`result`.

        Round 21: ``priority`` picks the request's class queue (higher =
        more important, weighted-fair dequeue); a request that arrives
        with its deadline already spent is shed HERE — terminal
        :class:`~serve_pool.RequestShed`, never queued, never routed."""
        if self._draining:
            raise RuntimeError("router is draining: admission closed")
        if dataclasses.is_dataclass(config) and not isinstance(config, type):
            config = dataclasses.asdict(config)
        config = dict(config or {})
        unknown = sorted(set(config) - set(CONFIG_KEYS))
        if unknown:
            raise ValueError(
                f"unknown generation config keys {unknown}; valid: "
                f"{list(CONFIG_KEYS)}"
            )
        priority = int(priority)
        if priority < 0:
            raise ValueError(f"priority must be >= 0, got {priority}")
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("empty prompt")
        now = self.clock()
        rid = self._next_rid
        self._next_rid += 1
        trace = tracing.new_trace_id()
        req = _FleetRequest(
            rid, trace, tokens, config,
            None if deadline_s is None else now + float(deadline_s),
            deadline_s, now, priority,
        )
        self._by_rid[rid] = req
        self._by_trace[trace] = req
        if deadline_s is not None and float(deadline_s) <= 0.0:
            # Arrived dead: shed at submit — it must never occupy queue
            # space or cost a route (round-21 satellite).
            self.metrics.counter("fleet_submitted_total").inc()
            self._emit_submit(req)
            self._shed(req, now, reason="expired_at_submit")
            return rid
        self._enqueue(req)
        self.metrics.counter("fleet_submitted_total").inc()
        self._emit_submit(req)
        return rid

    def _emit_submit(self, req: _FleetRequest) -> None:
        # The priority field appears ONLY when non-zero: default-path
        # journals stay byte-identical to round 16.
        self.journal.emit(
            "request_submit",
            rid=req.rid,
            trace=req.trace,
            prompt_len=len(req.tokens),
            max_new=int(req.config.get("max_new", 64)),
            greedy=bool(req.config.get("greedy", True)),
            **({"priority": req.priority} if req.priority else {}),
        )

    # -- per-class queues (round 21) ---------------------------------------

    def _enqueue(self, req: _FleetRequest) -> None:
        self._queues.setdefault(req.priority, deque()).append(req)

    def _requeue_front(self, req: _FleetRequest) -> None:
        self._queues.setdefault(req.priority, deque()).appendleft(req)

    def _queue_len(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _queued(self):
        for p in sorted(self._queues, reverse=True):
            yield from self._queues[p]

    def step(self) -> bool:
        """One router tick: collect results (every mailbox, dead
        replicas included — committed results survive their writer),
        supervise (verdicts → failover + relaunch scheduling), relaunch
        due members, shed overdue queued requests, route. Returns True
        while requests are outstanding."""
        if not self._started:
            self.start()
        now = self.clock()
        self._collect()
        self._breaker_scan(now)
        self._supervise(now)
        self._relaunch_due(now)
        self._shed_overdue(now)
        self._route(now)
        return not self.done_all()

    def wait_until_up(
        self, n: int | None = None, *, timeout_s: float = 600.0
    ) -> None:
        """Block until ``n`` replicas (default: all) have confirmed a
        good /healthz — the readiness gate between spawning a fleet and
        pointing traffic at it (replica startup is a jax import + restore
        + first compile; measuring it into TTFT would misstate serving)."""
        want = len(self.replicas) if n is None else int(n)
        deadline = self.clock() + timeout_s
        while True:
            self.step()
            up = sum(h.state == "up" for h in self.replicas.values())
            if up >= want:
                return
            if self.clock() > deadline:
                raise TimeoutError(
                    f"only {up}/{want} replicas up after {timeout_s}s "
                    f"({ {h.name: h.state for h in self.replicas.values()} })"
                )
            self.sleep(self.poll_interval)

    def done_all(self) -> bool:
        return self._queue_len() == 0 and all(
            r.terminal for r in self._by_rid.values()
        )

    def run_until_done(self, *, timeout_s: float | None = None) -> None:
        deadline = None if timeout_s is None else self.clock() + timeout_s
        while self.step():
            if deadline is not None and self.clock() > deadline:
                raise TimeoutError(
                    f"fleet did not finish within {timeout_s}s "
                    f"({self.stats()})"
                )
            self.sleep(self.poll_interval)

    def done(self, rid: int) -> bool:
        return self._by_rid[rid].terminal

    def result(self, rid: int) -> list[int]:
        """The served stream (router copy; consumes the record). Raises
        the same typed :class:`~serve_pool.RequestCancelled` as
        ``TextServer.result`` for a deadline-cancelled request,
        :class:`~serve_pool.RequestShed` for one the scheduler dropped
        before routing/prefill, and a RuntimeError naming the replica's
        error for a terminally rejected one."""
        req = self._by_rid[rid]
        if req.shed:
            del self._by_rid[rid]
            self._by_trace.pop(req.trace, None)
            raise RequestShed(
                f"request {rid} was shed before serving (deadline "
                "unreachable or displaced under overload)"
            )
        if req.cancelled:
            del self._by_rid[rid]
            self._by_trace.pop(req.trace, None)
            raise RequestCancelled(
                f"request {rid} was cancelled (deadline)"
            )
        if req.failed is not None:
            del self._by_rid[rid]
            self._by_trace.pop(req.trace, None)
            raise RuntimeError(f"request {rid} was rejected: {req.failed}")
        if not req.done:
            raise RuntimeError(f"request {rid} is not finished")
        del self._by_rid[rid]
        self._by_trace.pop(req.trace, None)
        return list(req.out)

    def generate(self, prompts, configs=None, *, timeout_s=None):
        """Submit a batch and serve it to completion (bench/test sugar)."""
        if configs is None or isinstance(configs, dict) or (
            dataclasses.is_dataclass(configs) and not isinstance(configs, type)
        ):
            configs = [configs] * len(prompts)
        rids = [
            self.submit(p, c) for p, c in zip(prompts, configs, strict=True)
        ]
        self.run_until_done(timeout_s=timeout_s)
        return [self.result(r) for r in rids]

    def swap_weights(self, checkpoint_dir: str | None = None) -> None:
        """Tell every live replica to adopt the newest CRC-verified
        checkpoint (optionally from a new directory) between chunk
        boundaries — the publish step of train→publish→serve. Each
        replica swaps independently; residents finish on old weights."""
        if checkpoint_dir is not None:
            self.current_checkpoint_dir = checkpoint_dir
        targets = [
            h for h in self.replicas.values() if h.state != "benched"
        ]
        for h in targets:
            payload: dict = {"control": "swap"}
            if checkpoint_dir is not None:
                payload["checkpoint_dir"] = checkpoint_dir
            h.client.control(payload)
            if self._prefix_index is not None:
                # The swap flushes the replica's radix (stale-weights
                # K/V): forget the fleet-level beliefs with it.
                self._prefix_index.drop_replica(h.name)
        self.journal.emit(
            "weight_swap_requested",
            source=checkpoint_dir,
            replicas=[h.name for h in targets],
        )

    def drain(self, *, timeout_s: float | None = None) -> None:
        """Close router admission and serve everything outstanding."""
        self._draining = True
        self.run_until_done(timeout_s=timeout_s)

    def shutdown(self) -> None:
        """Stop the fleet: ask every replica to exit its loop (graceful —
        the worker drains residents first), then reap/kill."""
        for h in self.replicas.values():
            try:
                h.client.control({"control": "stop"})
            except OSError:  # pragma: no cover — mailbox dir removed
                pass
        deadline = self.clock() + 30.0
        for h in self.replicas.values():
            if h.agent is None:
                continue
            while h.agent.poll() is None and self.clock() < deadline:
                self.sleep(self.poll_interval)
            h.agent.kill()
        self.journal.flush()

    def stats(self) -> dict:
        reqs = list(self._by_rid.values())
        return {
            "submitted": self._next_rid,
            "done": sum(r.done for r in reqs),
            "cancelled": sum(r.cancelled for r in reqs),
            "shed": sum(r.shed for r in reqs),
            "failed": sum(r.failed is not None for r in reqs),
            "queued": self._queue_len(),
            "inflight": sum(
                len(h.inflight) for h in self.replicas.values()
            ),
            "failovers": int(
                self.metrics.counter("failovers_total").value
            ),
            "reroutes": int(self.metrics.counter("reroutes_total").value),
            "replicas": {
                h.name: h.state for h in self.replicas.values()
            },
        }

    # -- the state machine -------------------------------------------------

    def _collect(self) -> None:
        for h in self.replicas.values():
            for payload in h.client.poll_results():
                # Any collected payload proves the mailbox round-trip is
                # alive: reset the breaker's consecutive-failure count
                # (and close it, if a half-open probe just came back).
                self._breaker_success(h)
                trace = payload.get("trace")
                # Pop BEFORE the dedupe check: a duplicate result (the
                # request already completed elsewhere) must still clear
                # this replica's inflight entry, or phantom load
                # accumulates and the replica reads saturated forever.
                h.inflight.pop(trace, None)
                req = self._by_trace.get(trace)
                if req is None or req.terminal:
                    continue  # dedupe: first terminal result won
                if payload.get("rejected"):
                    # A stale bounce (the request already failed over to
                    # another replica) must not re-queue a request that
                    # is live elsewhere — only the current owner's
                    # rejection counts. Stale COMPLETED results below
                    # are different: a committed stream is valid
                    # whoever serves the request now (first wins).
                    if req.replica == h.name:
                        self._rejected(h, req, payload)
                elif payload.get("cancelled"):
                    req.cancelled = True
                    req.t_done = self.clock()
                    self._cleanup_post(req)
                    self.metrics.counter("fleet_cancelled_total").inc()
                    self.journal.emit(
                        "fleet_result",
                        trace=trace,
                        rid=req.rid,
                        replica=h.name,
                        status="cancelled",
                    )
                elif payload.get("shed"):
                    # The replica's own scheduler shed it (queued there
                    # past its deadline / displaced under saturation).
                    req.shed = True
                    req.t_done = self.clock()
                    self._cleanup_post(req)
                    self.metrics.counter("fleet_shed_total").inc()
                    self.journal.emit(
                        "fleet_result",
                        trace=trace,
                        rid=req.rid,
                        replica=h.name,
                        status="shed",
                    )
                elif payload.get("migrated"):
                    # Leg 1 (prefill + first token) finished: schedule
                    # the decode leg under the SAME trace/rid. A failed
                    # post (post=None — the fleet.migrate failpoint, a
                    # full disk) degrades to re-prefill on the decode
                    # replica: slower, never lost. Only the current
                    # owner's report counts (stale-bounce rule above).
                    if req.replica == h.name:
                        req.leg = "decode"
                        req.resume_post = payload.get("post")
                        req.prefill_replica = h.name
                        req.leg1_tokens = [
                            int(t) for t in payload.get("tokens", [])
                        ]
                        req.replica = None
                        self.metrics.counter("fleet_migrations_total").inc()
                        self.journal.emit(
                            "request_migrated",
                            trace=trace,
                            rid=req.rid,
                            from_replica=h.name,
                            post=req.resume_post,
                            blocks=payload.get("blocks"),
                            nbytes=payload.get("nbytes"),
                        )
                        self._requeue_front(req)
                else:
                    req.out = [int(t) for t in payload.get("tokens", [])]
                    req.done = True
                    req.t_done = self.clock()
                    self._cleanup_post(req)
                    if req.out and req.t_routed is not None:
                        # Route-to-result seconds per emitted token: the
                        # hopeless-shed predicate's evidence. Includes
                        # replica-side queueing by design — that IS the
                        # completion-time a queued request faces.
                        inst = max(req.t_done - req.t_routed, 0.0) / len(
                            req.out
                        )
                        self._tok_ewma = (
                            inst
                            if self._tok_ewma is None
                            else 0.8 * self._tok_ewma + 0.2 * inst
                        )
                    self.metrics.counter("fleet_completions_total").inc()
                    self.journal.emit(
                        "fleet_result",
                        trace=trace,
                        rid=req.rid,
                        replica=h.name,
                        status="done",
                        tokens=len(req.out),
                        latency_s=round(req.t_done - req.t_submit, 6),
                        reroutes=max(req.attempts - 1, 0),
                    )

    def _cleanup_post(self, req: _FleetRequest) -> None:
        """The router owns a migration post's lifetime: remove it once
        its request is terminal (a decode-leg failover before then
        re-imports the SAME post — that is why the importer never
        deletes)."""
        if req.resume_post is not None and self._migrate is not None:
            self._migrate.remove(req.resume_post)

    def _rejected(self, h: ReplicaHandle, req, payload: dict) -> None:
        """A replica bounced the request. QueueFull is pure BACKPRESSURE:
        re-queue, cool the replica for a probe interval (the health doc
        the router routed on was stale), and charge NO budget — a
        saturated-but-healthy fleet holds requests, it never fails them.
        PERMANENT rejections (the replica's validation — geometry no
        replica will ever accept) and unknown rejection kinds past the
        re-route budget fail TERMINALLY: retrying a deterministic
        refusal forever would spin the router and never finish
        ``drain()``."""
        kind = payload.get("error_kind")
        if kind == "QueueFull":
            h.cooldown_until = self.clock() + self.probe_interval_s
            self.metrics.counter("reroutes_total").inc()
            self.journal.emit(
                "request_reroute",
                trace=req.trace,
                rid=req.rid,
                from_replica=h.name,
                attempt=req.attempts,
                reason="backpressure",
            )
            req.replica = None
            self._requeue_front(req)
            return
        permanent = kind in ("ValueError", "TypeError")
        if permanent or req.attempts > self.max_reroutes:
            req.failed = payload.get("error") or (
                f"routed {req.attempts} times (budget {self.max_reroutes})"
            )
            req.t_done = self.clock()
            self._cleanup_post(req)
            self.metrics.counter("fleet_failed_total").inc()
            self.journal.emit(
                "fleet_result",
                trace=req.trace,
                rid=req.rid,
                replica=h.name,
                status="rejected",
                error=req.failed,
            )
            return
        self.metrics.counter("reroutes_total").inc()
        self.journal.emit(
            "request_reroute",
            trace=req.trace,
            rid=req.rid,
            from_replica=h.name,
            attempt=req.attempts,
            reason="rejected",
        )
        req.replica = None
        self._requeue_front(req)  # older than anything queued behind it

    def _supervise(self, now: float) -> None:
        for h in self.replicas.values():
            if h.state not in ("starting", "up"):
                continue
            verdict = None
            rc = h.agent.poll() if h.agent is not None else None
            if rc is not None:
                # A serving replica has no legitimate self-exit while
                # supervised — rc 0 (a stop it was never sent) is as dead
                # as a SIGKILL.
                verdict = f"rc={rc}"
            elif h.health is not None and now >= h._next_probe:
                h._next_probe = now + self.probe_interval_s
                v = h.health.classify()
                if v != "ok":
                    verdict = v
                elif h.state == "starting" and h.health.last is not None:
                    h.state = "up"  # first good /healthz: routable
                    if self.current_checkpoint_dir is not None:
                        # Swap durability across relaunches: a fresh
                        # incarnation restored from its spawn-time
                        # directory and cleared its inbox — re-send the
                        # fleet's current serve dir (a replica already
                        # on it no-ops: swap_from_checkpoint adopts
                        # only NEWER steps).
                        h.client.control(
                            {
                                "control": "swap",
                                "checkpoint_dir":
                                    self.current_checkpoint_dir,
                            }
                        )
            if verdict is not None:
                self._fail(h, verdict)
        self.metrics.gauge("replicas_up").set(
            sum(h.state == "up" for h in self.replicas.values())
        )

    def _fail(self, h: ReplicaHandle, verdict: str) -> None:
        if h.agent is not None:
            h.agent.kill()  # stalled/health-dead: make the death real
        rerouted = [r for r in h.inflight.values() if not r.terminal]
        for req in reversed(rerouted):
            # Zero-loss re-admission: full config + the SAME trace id go
            # back to the queue front (original relative order kept), so
            # the retried stream is token-identical and the journal shows
            # one request across replicas. attempts counts ROUTES only
            # (incremented in _route) — one number, one meaning.
            req.replica = None
            self.metrics.counter("reroutes_total").inc()
            self.journal.emit(
                "request_reroute",
                trace=req.trace,
                rid=req.rid,
                from_replica=h.name,
                attempt=req.attempts,
                reason="replica_dead",
            )
            self._requeue_front(req)
        h.inflight.clear()
        h.breaker_reset()  # supervision owns the replica now
        if self._prefix_index is not None:
            # A dead replica's radix died with it: forget every warm-
            # prefix belief so the prefill leg stops preferring a ghost.
            self._prefix_index.drop_replica(h.name)
        h.attempts += 1
        self.metrics.counter("failovers_total").inc()
        lifecycle_event(
            "replica_dead",
            print_fn=self.print_fn,
            journal=self.journal,
            replica=h.name,
            verdict=verdict,
            rerouted=len(rerouted),
            attempt=h.attempts,
            max_restarts=self.max_restarts,
        )
        if h.attempts > self.max_restarts or h.agent is None:
            h.state = "benched"
            lifecycle_event(
                "replica_benched",
                print_fn=self.print_fn,
                journal=self.journal,
                replica=h.name,
                restarts=h.attempts,
                max_restarts=self.max_restarts,
            )
            active = [
                x for x in self.replicas.values() if x.state != "benched"
            ]
            if len(active) < self.min_replicas:
                lifecycle_event(
                    "fleet_below_floor",
                    print_fn=self.print_fn,
                    journal=self.journal,
                    replicas=len(active),
                    min_replicas=self.min_replicas,
                    cause=f"{h.name}={verdict}",
                )
                raise FleetBelowFloor({h.name: verdict})
        else:
            h.backoff_s = resilience.backoff_delay(
                h.attempts - 1,
                backoff=self.backoff,
                max_backoff=self.max_backoff,
                jitter=self.jitter,
                rng=self.rng,
            )
            h.state = "backoff"
            h.relaunch_at = self.clock() + h.backoff_s

    def _relaunch_due(self, now: float) -> None:
        for h in self.replicas.values():
            if h.state != "backoff" or now < (h.relaunch_at or 0.0):
                continue
            clear = getattr(h.client, "clear_inbox", None)
            if clear is not None:
                clear()  # stale routed work already failed over
            if h.health is not None:
                h.health.reset()  # fresh grace clock for the new process
            h.agent.start()
            h.state = "starting" if h.health is not None else "up"
            h.relaunch_at = None
            self.metrics.counter("relaunches_total").inc()
            lifecycle_event(
                "replica_relaunch",
                print_fn=self.print_fn,
                journal=self.journal,
                replica=h.name,
                attempt=h.attempts,
                max_restarts=self.max_restarts,
                backoff_s=h.backoff_s,
            )

    def _hopeless(self, req: _FleetRequest, now: float) -> bool:
        """Provably cannot finish: full remaining budget at the fleet's
        measured per-token pace overruns the slack. Conservative by
        construction — no EWMA yet means no verdict."""
        if req.deadline is None or self._tok_ewma is None:
            return False
        max_new = int(req.config.get("max_new", 64))
        return max_new * self._tok_ewma > req.deadline - now

    def _shed(self, req: _FleetRequest, now: float, *, reason: str) -> None:
        req.shed = True
        req.t_done = now
        self._cleanup_post(req)
        self.metrics.counter("fleet_shed_total").inc()
        self.journal.emit(
            "request_shed",
            rid=req.rid,
            trace=req.trace,
            priority=req.priority,
            reason=reason,
            age_s=round(now - req.t_submit, 6),
        )

    def _shed_overdue(self, now: float) -> None:
        """Router-side deadline enforcement for QUEUED requests (resident
        ones are cancelled replica-side and report back as cancelled).
        Round 21: a queued request past its deadline — or hopeless
        (:meth:`_hopeless`) — is SHED before a route is spent on it.
        A shed request is terminal: failover never resurrects it."""
        for prio in list(self._queues):
            q = self._queues[prio]
            if not any(
                r.deadline is not None
                and (now > r.deadline or self._hopeless(r, now))
                for r in q
            ):
                continue
            keep: deque[_FleetRequest] = deque()
            for req in q:
                if req.deadline is not None and now > req.deadline:
                    self._shed(req, now, reason="expired")
                elif self._hopeless(req, now):
                    self._shed(req, now, reason="hopeless")
                else:
                    keep.append(req)
            if keep:
                self._queues[prio] = keep
            else:
                del self._queues[prio]

    # -- circuit breaker (round 21) ----------------------------------------

    def _breaker_scan(self, now: float) -> None:
        """Per-replica circuit breaker: consecutive route timeouts open
        it, diverting routes IMMEDIATELY — before the slower HttpHealth
        verdict lands; after ``breaker_reset_s`` it half-opens and admits
        ONE probe; any collected result closes it (``_breaker_success``).
        Pure routing layer: no kill, no relaunch, nothing charged to the
        restart budget. ``route_timeout_s=None`` (default) disarms the
        timeout detector — round-16 behavior, byte-identical."""
        for h in self.replicas.values():
            if h.breaker == "open" and now >= h.breaker_until:
                h.breaker = "half_open"
                h.breaker_probe = None
                lifecycle_event(
                    "breaker_half_open",
                    print_fn=self.print_fn,
                    journal=self.journal,
                    replica=h.name,
                )
            if self.route_timeout_s is None or h.state != "up":
                continue
            timed_out = sorted(
                (
                    r
                    for r in h.inflight.values()
                    if not r.terminal
                    and r.t_routed is not None
                    and now - r.t_routed > self.route_timeout_s
                ),
                key=lambda r: r.rid,
            )
            for req in reversed(timed_out):
                h.inflight.pop(req.trace, None)
                req.replica = None
                self.metrics.counter("reroutes_total").inc()
                self.journal.emit(
                    "request_reroute",
                    trace=req.trace,
                    rid=req.rid,
                    from_replica=h.name,
                    attempt=req.attempts,
                    reason="route_timeout",
                )
                self._requeue_front(req)
            if timed_out:
                self._breaker_failure(
                    h, now, reason=f"{len(timed_out)} route timeout(s)"
                )

    def _breaker_failure(
        self, h: ReplicaHandle, now: float, *, reason: str
    ) -> None:
        h.breaker_failures += 1
        if h.breaker == "half_open":
            # The one probe failed: straight back to open.
            self._breaker_trip(h, now, reason=f"probe failed: {reason}")
        elif (
            h.breaker == "closed"
            and h.breaker_failures >= self.breaker_failures
        ):
            self._breaker_trip(h, now, reason=reason)

    def _breaker_trip(
        self, h: ReplicaHandle, now: float, *, reason: str
    ) -> None:
        h.breaker = "open"
        h.breaker_until = now + self.breaker_reset_s
        h.breaker_probe = None
        self.metrics.counter("breaker_opens_total").inc()
        lifecycle_event(
            "breaker_open",
            print_fn=self.print_fn,
            journal=self.journal,
            replica=h.name,
            failures=h.breaker_failures,
            reason=reason,
            reset_s=self.breaker_reset_s,
        )
        # Divert everything still routed there: the breaker's whole
        # point is not leaving work parked on a suspect replica until
        # the health verdict. Dedupe-on-trace keeps a late committed
        # result valid (first terminal wins), so diverting early is
        # free of double-serve risk.
        stuck = sorted(
            (r for r in h.inflight.values() if not r.terminal),
            key=lambda r: r.rid,
        )
        for req in reversed(stuck):
            h.inflight.pop(req.trace, None)
            req.replica = None
            self.metrics.counter("reroutes_total").inc()
            self.journal.emit(
                "request_reroute",
                trace=req.trace,
                rid=req.rid,
                from_replica=h.name,
                attempt=req.attempts,
                reason="breaker_open",
            )
            self._requeue_front(req)

    def _breaker_success(self, h: ReplicaHandle) -> None:
        h.breaker_failures = 0
        h.breaker_probe = None
        if h.breaker != "closed":
            h.breaker = "closed"
            lifecycle_event(
                "breaker_close",
                print_fn=self.print_fn,
                journal=self.journal,
                replica=h.name,
            )

    def _saturated(self, h: ReplicaHandle) -> bool:
        if self.clock() < h.cooldown_until:
            return True  # it just bounced a request: let it drain a beat
        doc = h.health.last if h.health is not None else None
        if not doc:
            return False
        sat = doc.get("queue_saturation")
        if isinstance(sat, (int, float)) and sat >= self.spill_threshold:
            return True
        lim = doc.get("queue_limit")
        if lim:
            # Router-side view: everything we routed and have not seen a
            # result for occupies a slot or a queue position there.
            return len(h.inflight) >= int(doc.get("slots", 0)) + int(lim)
        return False

    def _affinity_key(self, req: _FleetRequest):
        if self.affinity_tokens <= 0:
            return None
        return tuple(req.tokens[: self.affinity_tokens])

    def _pick(self, req: _FleetRequest) -> ReplicaHandle | None:
        routable = [h for h in self.replicas.values() if h.routable]
        if not routable:
            return None
        if self._two_leg:
            return self._pick_role(req, routable)
        key = self._affinity_key(req)
        if key is not None:
            sticky = self.replicas.get(self._affinity.get(key, ""), None)
            if (
                sticky is not None
                and sticky.routable
                and not self._saturated(sticky)
            ):
                self._affinity.pop(key, None)  # LRU refresh on hit
                self._affinity[key] = sticky.name
                return sticky
        open_ = [h for h in routable if not self._saturated(h)]
        if not open_:
            return None  # whole fleet saturated: hold at the router
        pick = min(open_, key=lambda h: len(h.inflight))
        if key is not None:
            # (Re)stick the prefix to the replica now warming its radix —
            # a dead sticky target is reassigned here, not mourned. The
            # map is LRU-bounded: unique-prompt traffic must not grow a
            # long-lived router's memory without limit.
            self._affinity.pop(key, None)
            self._affinity[key] = pick.name  # newest at the end
            while len(self._affinity) > self.affinity_cap:
                self._affinity.pop(next(iter(self._affinity)))
        return pick

    def _pick_role(
        self, req: _FleetRequest, routable: list[ReplicaHandle]
    ) -> ReplicaHandle | None:
        """Role-aware pick for disaggregated fleets (round 23). The leg
        decides the candidate pool (prefill-capable for the first leg,
        decode-capable for the resumed one); when no capable replica is
        routable, ANY routable replica serves the request whole — roles
        are scheduling policy, every replica runs the full engine, so a
        degraded fleet stays correct, just un-specialized. The prefill
        leg prefers the replica the fleet-wide prefix index says holds
        the deepest warm prefix, provided it is in the open pool. With
        ``migrate_threshold`` set, a first leg whose prompt is shorter
        than the threshold targets the DECODE pool instead — it serves
        whole where it would decode anyway, skipping a handoff that
        costs more than the prefill it would offload."""
        short = (
            req.leg != "decode"
            and self.migrate_threshold is not None
            and len(req.tokens) < self.migrate_threshold
        )
        want = (
            (lambda h: h.can_decode)
            if req.leg == "decode" or short
            else (lambda h: h.can_prefill)
        )
        pool = [h for h in routable if want(h)] or routable
        open_ = [h for h in pool if not self._saturated(h)]
        if not open_:
            return None  # capable pool saturated: hold at the router
        if req.leg != "decode" and self._prefix_index is not None:
            name, depth = self._prefix_index.lookup(req.tokens)
            if depth > 0 and name is not None:
                warm = self.replicas.get(name)
                if warm is not None and warm in open_:
                    return warm
        return min(open_, key=lambda h: len(h.inflight))

    def _next_queued(self) -> tuple[int, int] | None:
        """(priority, index) of the next dequeue candidate: weighted-fair
        ACROSS classes (deficit round robin, weight ``priority+1`` — low
        classes always progress, high classes get the larger share;
        replenished classes serve highest-first), earliest-deadline-first
        WITHIN a class (key ``(deadline-or-inf, rid)``; all-default
        traffic degenerates to the FIFO head — see the ``_queues``
        comment in ``__init__``)."""
        classes = sorted(
            (p for p, q in self._queues.items() if q), reverse=True
        )
        if not classes:
            return None
        if len(classes) == 1:
            prio = classes[0]
        else:
            funded = [p for p in classes if self._drr.get(p, 0.0) >= 1.0]
            if not funded:
                self._drr = {
                    p: self._drr.get(p, 0.0) + (p + 1) for p in classes
                }
                funded = classes
            prio = funded[0]
        q = self._queues[prio]
        idx = min(
            range(len(q)),
            key=lambda i: (
                math.inf if q[i].deadline is None else q[i].deadline,
                q[i].rid,
            ),
        )
        return prio, idx

    def _route(self, now: float) -> None:
        while True:
            nxt = self._next_queued()
            if nxt is None:
                return
            prio, idx = nxt
            q = self._queues[prio]
            req = q[idx]
            if req.terminal:
                # Became terminal while queued (a dead replica's
                # committed result arrived after the failover re-queue):
                # routing it again would re-serve a DONE request.
                del q[idx]
                if not q:
                    del self._queues[prio]
                continue
            if self._two_leg and req.leg == "single":
                req.leg = "prefill"  # first leg of a disaggregated request
            h = self._pick(req)
            if h is None:
                return
            # Charge DRR credit only while classes actually compete — a
            # lone class dequeues by the fast path above and must not
            # accumulate debt against classes that appear later.
            contested = sum(1 for qq in self._queues.values() if qq) > 1
            del q[idx]
            if not q:
                del self._queues[prio]
            if contested:
                self._drr[prio] = self._drr.get(prio, 0.0) - 1.0
            req.replica = h.name
            req.attempts += 1
            req.t_routed = now
            h.inflight[req.trace] = req
            if h.breaker == "half_open":
                h.breaker_probe = req.trace  # the one probe in flight
            payload = {
                "trace": req.trace,
                "tokens": req.tokens,
                "config": req.config,
            }
            if req.priority:
                payload["priority"] = req.priority
            if req.deadline is not None:
                payload["deadline_s"] = max(req.deadline - now, 0.0)
            if req.leg == "prefill" and h.role == "prefill":
                # Migrate only off a prefill-SPECIALIZED replica — a
                # "both" (or fallback decode) target just serves the
                # request whole; the handoff would be pure overhead.
                payload["migrate"] = True
            elif req.leg == "decode":
                if req.resume_post is not None:
                    payload["resume"] = req.resume_post
                    payload["emitted"] = req.leg1_tokens or []
                # resume_post None = the prefill leg's post failed or was
                # quarantined: the decode replica re-prefills from the
                # prompt (full re-serve, stream identical by parity).
            if self._prefix_index is not None and req.leg != "decode":
                # Optimistic: this replica is about to warm these prompt
                # blocks. A died-before-prefill entry is self-healing —
                # _fail drops the replica's entries wholesale.
                self._prefix_index.insert(req.tokens, h.name)
            try:
                h.client.submit(payload)
            except OSError as exc:
                # Transport failure counts as a breaker failure; the
                # request goes back to its queue front uncharged. Stop
                # routing this tick — retrying the same pick in a tight
                # loop would spin until the breaker trips.
                h.inflight.pop(req.trace, None)
                req.replica = None
                self.metrics.counter("reroutes_total").inc()
                self.journal.emit(
                    "request_reroute",
                    trace=req.trace,
                    rid=req.rid,
                    from_replica=h.name,
                    attempt=req.attempts,
                    reason="submit_error",
                )
                self._requeue_front(req)
                self._breaker_failure(
                    h, now, reason=f"submit {type(exc).__name__}"
                )
                return
            self.metrics.counter("routed_total").inc()
            route_kw = {}
            if req.leg != "single":
                route_kw["leg"] = req.leg
            self.journal.emit(
                "request_route",
                trace=req.trace,
                rid=req.rid,
                replica=h.name,
                attempt=req.attempts,
                queue_wait_s=round(now - req.t_submit, 6),
                **route_kw,
            )


# ---------------------------------------------------------------------------
# Local subprocess fleet (the launch_local analog for serving).
# ---------------------------------------------------------------------------


def port_file(replica_dir: str) -> str:
    """Where a replica publishes its ephemeral /healthz port."""
    return os.path.join(replica_dir, "port.json")


def replica_url(replica_dir: str) -> str | None:
    """The replica's /healthz URL, or None until the port is published."""
    try:
        with open(port_file(replica_dir), encoding="utf-8") as f:
            port = json.load(f)["port"]
    except (OSError, ValueError, KeyError):
        return None
    return f"http://127.0.0.1:{port}/healthz"


def local_fleet(
    model_kw: dict,
    checkpoint_dir: str,
    fleet_dir: str,
    *,
    replicas: int = 3,
    roles: list[str] | tuple[str, ...] | None = None,
    slots: int | list[int] | tuple[int, ...] = 4,
    chunk: int = 8,
    queue_limit: int = 32,
    buckets: tuple[int, ...] | None = None,
    paged: bool = False,
    block_size: int = 16,
    kv_blocks: int = 64,
    kv_dtype: str = "bf16",
    poll_s: float = 0.005,
    warm: bool = True,
    env: dict | None = None,
    grace_s: float = 300.0,
    dead_after_s: float = 10.0,
    print_fn=print,
    **router_kw,
) -> ReplicaRouter:
    """Build a router over N subprocess replicas on this host, each a
    ``run_replica`` worker (TextServer restored from ``checkpoint_dir``).
    ``model_kw`` are GPTLM constructor kwargs (JSON-serialized onto the
    worker's argv; ``compute_dtype`` as a dtype NAME string). Per-replica
    journals land at ``<fleet_dir>/events-<name>.jsonl`` (via
    ``DTF_EVENTS_PATH``) beside the router's ``events.jsonl`` — the files
    ``obs_report --fleet`` merges into one cross-replica timeline. The
    startup grace is generous by default: a cold jax import + restore on
    a loaded host must not read as death (CLAUDE.md's integration-test
    lesson). ``roles`` (one of ``prefill``/``decode``/``both`` per
    replica) arms the round-23 disaggregated two-leg path: any non-both
    role forces ``paged=True``, creates ``<fleet_dir>/migrate`` as the
    shared migration store, and passes ``migrate_dir`` to the router.
    ``slots`` may be a per-replica list — the role-tuning lever: decode
    replicas pack many resident streams (decode is memory-bound, round
    18), prefill replicas size to their batch-prefill width."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    os.makedirs(fleet_dir, exist_ok=True)
    slot_list = (
        [int(s) for s in slots]
        if isinstance(slots, (list, tuple))
        else [int(slots)] * replicas
    )
    if len(slot_list) != replicas:
        raise ValueError(
            f"slots has {len(slot_list)} entries for {replicas} replicas"
        )
    if roles is not None:
        if len(roles) != replicas:
            raise ValueError(
                f"roles has {len(roles)} entries for {replicas} replicas"
            )
        if any(r != "both" for r in roles):
            paged = True  # a disaggregated fleet migrates paged KV
    migrate_dir = None
    if roles is not None and any(r != "both" for r in roles):
        migrate_dir = os.path.join(fleet_dir, "migrate")
        os.makedirs(migrate_dir, exist_ok=True)
        router_kw.setdefault("migrate_dir", migrate_dir)
    run_id = f"fleet-{os.getpid()}"
    journal = EventJournal.in_dir(fleet_dir, run_id=run_id)
    handles = []
    for i in range(replicas):
        name = f"replica{i}"
        rdir = os.path.join(fleet_dir, name)
        os.makedirs(rdir, exist_ok=True)
        renv = dict(os.environ)
        renv.update(env or {})
        renv["DTF_EVENTS_PATH"] = os.path.join(
            fleet_dir, f"events-{name}.jsonl"
        )
        renv["DTF_RUN_ID"] = run_id
        cmd = [
            sys.executable, "-m", "distributed_tensorflow_tpu.serve_fleet",
            "--replica", "--dir", rdir,
            "--checkpoint-dir", checkpoint_dir,
            "--model", json.dumps(model_kw),
            "--slots", str(slot_list[i]), "--chunk", str(chunk),
            "--queue-limit", str(queue_limit), "--poll-s", str(poll_s),
        ]
        if buckets:
            cmd += ["--buckets", ",".join(str(b) for b in buckets)]
        if paged:
            cmd += [
                "--paged",
                "--block-size", str(block_size),
                "--kv-blocks", str(kv_blocks),
                "--kv-dtype", kv_dtype,
            ]
        if migrate_dir is not None:
            cmd += ["--migrate-dir", migrate_dir]
        if warm:
            cmd += ["--warm"]

        def _spawn(cmd=cmd, renv=renv, rdir=rdir, name=name):
            try:  # a relaunch must not probe the dead incarnation's port
                os.remove(port_file(rdir))
            except OSError:
                pass
            log = open(os.path.join(fleet_dir, f"{name}.log"), "ab")
            try:
                return subprocess.Popen(
                    cmd, stdout=log, stderr=subprocess.STDOUT, env=renv
                )
            finally:
                log.close()

        handles.append(
            ReplicaHandle(
                name,
                client=MailboxClient(rdir),
                agent=ElasticAgent(name, _spawn),
                health=HttpHealth(
                    (lambda rdir=rdir: replica_url(rdir)),
                    grace_s=grace_s,
                    dead_after_s=dead_after_s,
                ),
                role=roles[i] if roles is not None else "both",
            )
        )
    return ReplicaRouter(
        handles, journal=journal, print_fn=print_fn, **router_kw
    )


def publish_checkpoint(model, params, checkpoint_dir: str, step: int = 1):
    """Publish ``params`` as a dense, CRC-manifested ``step_N`` checkpoint
    that ``canonical_lm_params`` (and therefore every fleet replica)
    restores — the publish edge of train→publish→serve for callers that
    are not an LMTrainer: benches, tests, external trainers. Uses the
    reference-SGD optimizer whose slot state is empty, matching the
    serving restore default."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.ops import optim as optim_lib
    from distributed_tensorflow_tpu.parallel.strategy import TrainState
    from distributed_tensorflow_tpu.train.supervisor import Supervisor

    opt = optim_lib.sgd(0.001)
    Supervisor(checkpoint_dir=checkpoint_dir).save(
        TrainState(params, opt.init(params), jnp.asarray(step, jnp.int32)),
        int(step),
    )


# ---------------------------------------------------------------------------
# The replica worker (the only half that imports the engine / jax).
# ---------------------------------------------------------------------------

_DTYPES = {
    "float32": "float32",
    "bfloat16": "bfloat16",
    "float16": "float16",
}


def _model_from_kw(model_kw: dict):
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.models.gpt import GPTLM

    kw = dict(model_kw)
    cd = kw.get("compute_dtype")
    if isinstance(cd, str):
        if cd not in _DTYPES:
            raise ValueError(f"unknown compute_dtype {cd!r}")
        kw["compute_dtype"] = jnp.dtype(_DTYPES[cd])
    return GPTLM(**kw)


def run_replica(args) -> int:
    """One serving replica: TextServer from ``checkpoint_dir``, driven
    against the mailbox — admit at chunk boundaries, one ``step()`` per
    loop turn, results committed atomically the tick they finish (the
    zero-loss contract's write-before-crash half). SIGTERM is graceful:
    the loop exits, residents drain, results flush, rc 0 — the same
    preemption stance as the trainers (train/resilience.py)."""
    import signal

    from distributed_tensorflow_tpu.observability import (
        journal as obs_journal_mod,
    )
    from distributed_tensorflow_tpu.observability.exporter import (
        MetricsExporter,
    )
    from distributed_tensorflow_tpu.serve import (
        GenerationConfig,
        QueueFull,
        RequestCancelled,
        RequestShed,
        TextServer,
    )

    obs_journal_mod.configure_from_env(announce=True)
    model = _model_from_kw(json.loads(args.model))
    buckets = (
        tuple(int(b) for b in args.buckets.split(","))
        if args.buckets
        else None
    )
    srv_kw: dict = {}
    if args.paged:
        srv_kw.update(
            paged=True,
            block_size=args.block_size,
            kv_blocks=args.kv_blocks,
            kv_dtype=args.kv_dtype,
        )
    srv = TextServer.from_checkpoint(
        model,
        args.checkpoint_dir,
        slots=args.slots,
        chunk=args.chunk,
        buckets=buckets,
        queue_limit=args.queue_limit or None,
        **srv_kw,
    )
    box = MailboxClient(args.dir, metrics=srv.metrics)
    store = (
        MigrationStore(args.migrate_dir, metrics=srv.metrics)
        if getattr(args, "migrate_dir", None)
        else None
    )
    # A fresh incarnation serves only newly routed work: anything in the
    # inbox predates this process and already failed over elsewhere.
    box.clear_inbox()
    if args.warm:
        # Pre-warm every compiled surface (one prefill per bucket + the
        # chunk executable) BEFORE publishing the health port: a replica
        # that reads "up" is ready to serve at serving speed, and first-
        # request TTFT is not a compile measurement.
        import numpy as _np

        for b in srv.buckets:
            if b + 2 > model.max_len:
                continue
            srv.generate(
                [_np.arange(1, b + 1, dtype=_np.int32)],
                GenerationConfig(max_new=2),
            )
        if store is not None:
            # Decode replicas must not pay the import-scatter compile
            # on their first resumed request (see warm_import).
            srv.warm_import()
    def _health():
        # Round-21 satellite: mailbox corruption is a health-visible
        # signal, not a "silent replica by design" (known_issues.md) —
        # router verdicts and dashboards see the quarantine count.
        doc = srv.health()
        doc["mailbox_corrupt_files"] = box.corrupt_files + (
            store.corrupt_files if store is not None else 0
        )
        return doc

    exporter = MetricsExporter(srv.metrics, port=args.port, health_fn=_health)
    write_json_atomic(port_file(args.dir), {"port": exporter.start()})

    stop: list[int] = []
    prev = signal.signal(signal.SIGTERM, lambda *a: stop.append(1))

    def _flush_done(rids: dict) -> None:
        for rid in list(rids):
            if srv.done(rid):
                export = srv.take_export(rid) if store is not None else None
                if export is not None:
                    # Prefill leg finished: post the KV payload on the
                    # migration store, then hand the baton back to the
                    # router. A failed post is NOT a failed request —
                    # post=None tells the router the decode leg must
                    # re-prefill (the fallback matrix's cheap row).
                    trace = rids.pop(rid)
                    t0 = time.perf_counter()
                    nbytes = sum(
                        a.nbytes for a in export["arrays"].values()
                    )
                    try:
                        post = store.post(f"{trace}.npz", export)
                    except OSError as exc:
                        post = None
                        obs_journal_mod.get_journal().emit(
                            "kv_migration",
                            phase="post_failed",
                            trace=trace,
                            error=f"{type(exc).__name__}: {exc}",
                        )
                    else:
                        obs_journal_mod.get_journal().emit(
                            "kv_migration",
                            phase="post",
                            trace=trace,
                            file=post,
                            blocks=int(export["meta"]["blocks"]),
                            nbytes=int(nbytes),
                            wall_ms=round(
                                (time.perf_counter() - t0) * 1e3, 3
                            ),
                        )
                    box.put_result(
                        {
                            "trace": trace,
                            "migrated": True,
                            "post": post,
                            "tokens": [int(t) for t in export["tokens"]],
                            "blocks": int(export["meta"]["blocks"]),
                            "nbytes": int(nbytes),
                        }
                    )
                    continue
                trace = rids.pop(rid)
                try:
                    toks = srv.result(rid)
                    box.put_result(
                        {"trace": trace, "tokens": [int(t) for t in toks]}
                    )
                except RequestShed:
                    box.put_result({"trace": trace, "shed": True})
                except RequestCancelled:
                    box.put_result({"trace": trace, "cancelled": True})

    rids: dict[int, str] = {}
    try:
        while not stop:
            for payload in box.take_inbox():
                ctl = payload.get("control")
                if ctl == "stop":
                    stop.append(1)
                elif ctl == "swap":
                    # A bad publish (typo'd dir, all-corrupt steps) must
                    # cost the SWAP, never the replica: journal the
                    # failure and keep serving the current weights — the
                    # same stance the submit guard below takes for
                    # poison requests.
                    try:
                        srv.swap_from_checkpoint(
                            payload.get("checkpoint_dir")
                        )
                    except Exception as exc:  # noqa: BLE001
                        obs_journal_mod.get_journal().emit(
                            "weight_swap_failed",
                            source=payload.get("checkpoint_dir"),
                            error=f"{type(exc).__name__}: {exc}",
                        )
                elif ctl is not None:
                    continue  # unknown control: ignore, stay alive
                else:
                    # TypeError covers a malformed config dict (unknown
                    # GenerationConfig keys): reject it back to the
                    # router — a poison request must cost ITSELF, never
                    # the replica process (the router fails it terminally
                    # on the error_kind, so it cannot cascade either).
                    sub_kw: dict = {}
                    if payload.get("migrate") and store is not None:
                        sub_kw["prefill_only"] = True
                    post_name = payload.get("resume")
                    if post_name is not None and store is not None:
                        loaded = store.load(post_name)
                        if loaded is None:
                            # Missing or quarantined post: fall back to a
                            # full re-prefill on THIS replica — the warm
                            # radix stays, the stream stays identical.
                            obs_journal_mod.get_journal().emit(
                                "kv_migration",
                                phase="fallback",
                                trace=payload.get("trace"),
                                file=post_name,
                                reason="load_failed",
                            )
                        else:
                            sub_kw["resume"] = {
                                "arrays": loaded["arrays"],
                                "meta": loaded["meta"],
                            }
                            sub_kw["emitted_tokens"] = payload.get(
                                "emitted", loaded.get("tokens")
                            )
                    try:
                        try:
                            rid = srv.submit(
                                payload["tokens"],
                                GenerationConfig(
                                    **(payload.get("config") or {})
                                ),
                                deadline_s=payload.get("deadline_s"),
                                priority=int(payload.get("priority", 0)),
                                trace=payload.get("trace"),
                                **sub_kw,
                            )
                        except ValueError:
                            if "resume" not in sub_kw:
                                raise
                            # Geometry/dtype mismatch between the post and
                            # THIS replica's cache (heterogeneous fleet,
                            # mid-roll kv_dtype change): re-prefill here
                            # rather than bounce the request.
                            obs_journal_mod.get_journal().emit(
                                "kv_migration",
                                phase="fallback",
                                trace=payload.get("trace"),
                                file=post_name,
                                reason="resume_rejected",
                            )
                            rid = srv.submit(
                                payload["tokens"],
                                GenerationConfig(
                                    **(payload.get("config") or {})
                                ),
                                deadline_s=payload.get("deadline_s"),
                                priority=int(payload.get("priority", 0)),
                                trace=payload.get("trace"),
                            )
                    except (
                        QueueFull, ValueError, TypeError, RuntimeError,
                    ) as exc:
                        box.put_result(
                            {
                                "trace": payload.get("trace"),
                                "rejected": True,
                                "error_kind": type(exc).__name__,
                                "error": f"{type(exc).__name__}: {exc}",
                            }
                        )
                    else:
                        rids[rid] = payload["trace"]
            srv.step()
            _flush_done(rids)
            if srv.idle():
                time.sleep(args.poll_s)
        srv.drain()  # graceful: residents finish, nothing dropped
        _flush_done(rids)
    finally:
        signal.signal(signal.SIGTERM, prev)
        exporter.stop()
        obs_journal_mod.get_journal().flush()
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--replica", action="store_true",
        help="run as a replica worker (spawned by local_fleet)",
    )
    ap.add_argument("--dir", help="replica mailbox directory")
    ap.add_argument("--checkpoint-dir")
    ap.add_argument("--model", help="GPTLM constructor kwargs as JSON")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--queue-limit", type=int, default=32)
    ap.add_argument("--buckets", default=None, help="comma-separated")
    ap.add_argument(
        "--port", type=int, default=0,
        help="/healthz port (0 = ephemeral, published to <dir>/port.json)",
    )
    ap.add_argument("--poll-s", type=float, default=0.005)
    ap.add_argument(
        "--paged", action="store_true",
        help="serve from the paged KV pool (required for migration)",
    )
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--kv-blocks", type=int, default=64)
    ap.add_argument("--kv-dtype", default="bf16")
    ap.add_argument(
        "--migrate-dir", default=None,
        help="shared migration-store directory (arms the prefill→decode "
        "KV handoff; posts are CRC-enveloped npz files)",
    )
    ap.add_argument(
        "--warm", action="store_true",
        help="compile every prefill bucket + the chunk executable before "
        "publishing the health port (readiness == serving-ready)",
    )
    args = ap.parse_args(argv)
    if not args.replica:
        ap.error("only --replica mode has a CLI; drive routers in-process "
                 "(serve_fleet.local_fleet)")
    for req in ("dir", "checkpoint_dir", "model"):
        if getattr(args, req) in (None, ""):
            ap.error(f"--replica requires --{req.replace('_', '-')}")
    return run_replica(args)


if __name__ == "__main__":
    sys.exit(main())
