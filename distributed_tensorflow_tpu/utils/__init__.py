from distributed_tensorflow_tpu.utils.logging import StepLogger  # noqa: F401
from distributed_tensorflow_tpu.utils.summary import SummaryWriter  # noqa: F401
