"""Trustworthy execution barrier for timing code.

Through the tunneled TPU PJRT plugin, ``jax.block_until_ready`` returns
optimistically — timing against it measures *enqueue*, not execution (it
once reported "25 epochs in 1 ms"; see docs/performance.md for the full
post-mortem). The only barrier that provably waits for the device is a
device-to-host **value fetch** of a buffer that transitively depends on
the work being timed.

This is the one shared implementation of that rule (CLAUDE.md: "any new
timing code must too"). The reference's timing (AvgTime/Total Time around
blocking ``sess.run`` calls, reference tfdist_between.py:92-110) never had
the problem because ``sess.run`` fetches values; in JAX's async-dispatch
model the fetch must be explicit.
"""

from __future__ import annotations

import jax
import numpy as np


def d2h_barrier(tree) -> None:
    """Block until every computation ``tree`` depends on has executed, by
    copying one array leaf to host. Prefer fetching a value you already
    need (as ``bench.py`` does with the final cost); use this when the
    timed code produces nothing the caller wants on host.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        # Every device leaf, not just the first: leaves may come from
        # independent dispatches, and a host-numpy first leaf would make a
        # single-leaf fetch a silent no-op.
        if isinstance(leaf, jax.Array):
            np.asarray(leaf)
