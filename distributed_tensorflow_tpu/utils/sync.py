"""Trustworthy execution barrier for timing code.

Through the tunneled TPU PJRT plugin, ``jax.block_until_ready`` returns
optimistically — timing against it measures *enqueue*, not execution (it
once reported "25 epochs in 1 ms"; see docs/performance.md for the full
post-mortem). The only barrier that provably waits for the device is a
device-to-host **value fetch** of a buffer that transitively depends on
the work being timed.

This is the one shared implementation of that rule (CLAUDE.md: "any new
timing code must too"). The reference's timing (AvgTime/Total Time around
blocking ``sess.run`` calls, reference tfdist_between.py:92-110) never had
the problem because ``sess.run`` fetches values; in JAX's async-dispatch
model the fetch must be explicit.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def timed_fetch(fn, *args):
    """Run ``fn(*args)`` and return ``(seconds, result)`` with the clock
    read AFTER a one-scalar D2H fetch of the result — the ONE audited
    dispatch-timing wrapper (three hand copies of this four-liner existed
    and one of them read the clock before the fetch, timing enqueue; the
    round-4 trap CLAUDE.md documents). The barrier fetches a single
    element of the first array leaf (4 bytes through the ~6 MB/s tunnel —
    never the whole buffer): any output element becomes available only
    when the whole dispatch has executed."""
    t0 = time.perf_counter()
    out = fn(*args)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.reshape(leaf, (-1,))[0].astype(jnp.float32))
    return time.perf_counter() - t0, out


def two_point_seconds(time_short, time_long, span: int, reps: int = 5) -> float:
    """Per-unit seconds by the TWO-POINT method — the ONE audited
    implementation of the round-4 timing discipline (CLAUDE.md TIMING TRAP
    2; three hand copies had already drifted to reps 7/3/5 and one sized
    its span below the jitter floor).

    Each tunnel dispatch+fetch carries a ~100 ms fixed roundtrip with
    ~±10 ms jitter; dividing one chain's wall time by its length folds the
    roundtrip into every unit. Instead call ``time_short()`` and
    ``time_long()`` (each a full timed dispatch whose clock reads AFTER a
    D2H value fetch) and divide the difference by ``span`` (the extra
    units the long chain runs). Median over ``reps`` resists the jitter;
    the caller must size ``span`` so the differenced wall time dwarfs
    ~±10 ms — negative medians (span below the noise floor) are clamped
    to 1e-12, so a 0.0-looking result means "span too small", not "free".
    """
    deltas = []
    for _ in range(reps):
        t_short = time_short()
        t_long = time_long()
        deltas.append((t_long - t_short) / span)
    deltas.sort()
    return max(deltas[len(deltas) // 2], 1e-12)


def d2h_barrier(tree) -> None:
    """Block until every computation ``tree`` depends on has executed, by
    copying one array leaf to host. Prefer fetching a value you already
    need (as ``bench.py`` does with the final cost); use this when the
    timed code produces nothing the caller wants on host.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        # Every device leaf, not just the first: leaves may come from
        # independent dispatches, and a host-numpy first leaf would make a
        # single-leaf fetch a silent no-op.
        if isinstance(leaf, jax.Array):
            np.asarray(leaf)
