"""stdout observability (component C14's log surface, SURVEY.md §5).

Reproduces the reference's exact log lines so downstream tooling / eyeballs
that parsed the reference's output keep working:

- per-``freq``-batches: ``Step: N,  Epoch: E,  Batch: B of T,  Cost: C,
  AvgTime: Xms`` (reference tfdist_between.py:102-106)
- per-epoch: ``Test-Accuracy: A`` / ``Total Time: Ts`` (reference :109-110)
- end: ``Final Cost: C`` / ``Done`` (reference :112,115)

Round 10 (telemetry layer): every line is now rendered FROM a journal
event — ``StepLogger`` builds the typed event first (``step``/``epoch``/
``final``), emits it through the attached :class:`~observability.journal.
EventJournal` (a no-op :class:`NullJournal` when none is attached), and
prints :func:`observability.format.render`'s rendering of that event.
The stdout bytes are byte-identical to the pre-journal output (pinned by
tests/test_observability.py::test_step_logger_byte_parity); the journal
is a machine-readable superset, never a replacement.
"""

from __future__ import annotations

import time

from distributed_tensorflow_tpu.observability import format as obs_format
from distributed_tensorflow_tpu.observability import journal as obs_journal


class StepLogger:
    """Hot-loop logger with the reference's cadence and wording."""

    def __init__(self, freq: int = 100, print_fn=print, journal=None):
        self.freq = freq
        self._print = print_fn
        self.journal = journal if journal is not None else obs_journal.get_journal()
        self._begin_time = time.time()
        self._window_start = time.time()
        self._window_count = 0

    def reset_window(self) -> None:
        self._window_start = time.time()
        self._window_count = 0

    def is_due(self, count: int, batch_count: int) -> bool:
        """The reference's cadence (tfdist_between.py:99). The single source
        of truth — the trainer gates its host sync on this same predicate."""
        return count % self.freq == 0 or count == batch_count

    def _emit(self, kind: str, **fields) -> dict:
        """Journal the event, print its rendering — the event is the
        source; the line is a view of it."""
        return obs_format.emit_line(
            kind, journal=self.journal, print_fn=self._print, **fields
        )

    def log_step_line(
        self,
        *,
        step: int,
        epoch: int,
        batch: int,
        batch_count: int,
        cost: float,
        avg_ms: float,
    ) -> None:
        # Event fields carry the PRINTED (1-based) epoch/batch numbers, so
        # the journal reads the way the reference's logs always have.
        self._emit(
            "step",
            step=int(step),
            epoch=int(epoch) + 1,
            batch=int(batch) + 1,
            batch_count=int(batch_count),
            cost=float(cost),
            avg_ms=float(avg_ms),
        )

    def maybe_log_step(
        self, *, step: int, epoch: int, batch: int, batch_count: int, cost: float
    ) -> None:
        count = batch + 1
        if self.is_due(count, batch_count):
            elapsed = time.time() - self._window_start
            # Average over the batches actually in this window (the final
            # window of an epoch may be partial).
            window = max(count - self._window_count, 1)
            self.log_step_line(
                step=step,
                epoch=epoch,
                batch=batch,
                batch_count=batch_count,
                cost=cost,
                avg_ms=float(elapsed * 1000 / window),
            )
            self._window_count = count
            self._window_start = time.time()

    def log_epoch(self, *, test_accuracy: float) -> None:
        self._emit(
            "epoch",
            metric="Test-Accuracy",
            value=float(test_accuracy),
            total_time_s=float(time.time() - self._begin_time),
        )

    def log_epoch_metric(self, name: str, value: float) -> None:
        """Epoch line for non-accuracy metrics (the LM's perplexity) — same
        shape as the reference's Test-Accuracy/Total Time pair."""
        self._emit(
            "epoch",
            metric=str(name),
            value=float(value),
            total_time_s=float(time.time() - self._begin_time),
        )

    def log_final(self, *, cost: float) -> None:
        self._emit("final", cost=float(cost))
