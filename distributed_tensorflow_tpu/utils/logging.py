"""stdout observability (component C14's log surface, SURVEY.md §5).

Reproduces the reference's exact log lines so downstream tooling / eyeballs
that parsed the reference's output keep working:

- per-``freq``-batches: ``Step: N,  Epoch: E,  Batch: B of T,  Cost: C,
  AvgTime: Xms`` (reference tfdist_between.py:102-106)
- per-epoch: ``Test-Accuracy: A`` / ``Total Time: Ts`` (reference :109-110)
- end: ``Final Cost: C`` / ``Done`` (reference :112,115)
"""

from __future__ import annotations

import time


class StepLogger:
    """Hot-loop logger with the reference's cadence and wording."""

    def __init__(self, freq: int = 100, print_fn=print):
        self.freq = freq
        self._print = print_fn
        self._begin_time = time.time()
        self._window_start = time.time()
        self._window_count = 0

    def reset_window(self) -> None:
        self._window_start = time.time()
        self._window_count = 0

    def is_due(self, count: int, batch_count: int) -> bool:
        """The reference's cadence (tfdist_between.py:99). The single source
        of truth — the trainer gates its host sync on this same predicate."""
        return count % self.freq == 0 or count == batch_count

    def log_step_line(
        self,
        *,
        step: int,
        epoch: int,
        batch: int,
        batch_count: int,
        cost: float,
        avg_ms: float,
    ) -> None:
        self._print(
            "Step: %d," % step,
            " Epoch: %2d," % (epoch + 1),
            " Batch: %3d of %3d," % (batch + 1, batch_count),
            " Cost: %.4f," % cost,
            " AvgTime: %3.2fms" % avg_ms,
        )

    def maybe_log_step(
        self, *, step: int, epoch: int, batch: int, batch_count: int, cost: float
    ) -> None:
        count = batch + 1
        if self.is_due(count, batch_count):
            elapsed = time.time() - self._window_start
            # Average over the batches actually in this window (the final
            # window of an epoch may be partial).
            window = max(count - self._window_count, 1)
            self.log_step_line(
                step=step,
                epoch=epoch,
                batch=batch,
                batch_count=batch_count,
                cost=cost,
                avg_ms=float(elapsed * 1000 / window),
            )
            self._window_count = count
            self._window_start = time.time()

    def log_epoch(self, *, test_accuracy: float) -> None:
        self._print("Test-Accuracy: %2.2f" % test_accuracy)
        self._print("Total Time: %3.2fs" % float(time.time() - self._begin_time))

    def log_epoch_metric(self, name: str, value: float) -> None:
        """Epoch line for non-accuracy metrics (the LM's perplexity) — same
        shape as the reference's Test-Accuracy/Total Time pair."""
        self._print("%s: %.4f" % (name, value))
        self._print("Total Time: %3.2fs" % float(time.time() - self._begin_time))

    def log_final(self, *, cost: float) -> None:
        self._print("Final Cost: %.4f" % cost)
        self._print("Done")
