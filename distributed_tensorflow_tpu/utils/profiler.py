"""Profiling (SURVEY.md §5 "Tracing/profiling").

The reference's only instrumentation is hand-rolled wall-clock timing in the
loop (AvgTime/Total Time, reference tfdist_between.py:98-110) — kept as-is in
``utils/logging.py``. This module adds the TPU-native upgrade the survey
prescribes: ``jax.profiler`` traces (XLA op-level timelines viewable in
TensorBoard/Perfetto) and an on-demand profiling server.

Round 10: both wrappers compose with the host-side span layer
(``observability/spans.py``) — pass a :class:`~observability.spans.
SpanRecorder` and the device trace window / annotation also lands as a
host span, so ``obs_report --trace``'s chrome-trace export shows WHERE in
the run the device capture happened. The device trace remains the
authority on what the chip did; host spans are the authority on what the
host waited for (and their dispatch flavor enforces the D2H barrier that
``jax.profiler`` does not).
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(logdir: str, recorder=None):
    """Capture a device trace for the enclosed block::

        with profiler.trace("./logs/profile"):
            state, cost = train_step(state, x, y)
            float(cost)  # D2H fetch: the trustworthy barrier (utils/sync.py)

    ``recorder`` (a SpanRecorder) additionally records the capture window
    as a host span named ``jax_profiler_trace``."""
    ctx = (
        recorder.span("jax_profiler_trace", cat="profiler", logdir=logdir)
        if recorder is not None
        else contextlib.nullcontext()
    )
    with ctx:
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the on-demand profiling server (connect with TensorBoard's
    profile tab or `xprof`); returns the server object."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def annotate(name: str, recorder=None):
    """Named region on the device trace timeline — and, when ``recorder``
    is given, the same region as a host span (one name, both views)."""
    ctx = (
        recorder.span(name, cat="annotation")
        if recorder is not None
        else contextlib.nullcontext()
    )
    with ctx, jax.profiler.TraceAnnotation(name):
        yield
