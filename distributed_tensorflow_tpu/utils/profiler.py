"""Profiling (SURVEY.md §5 "Tracing/profiling").

The reference's only instrumentation is hand-rolled wall-clock timing in the
loop (AvgTime/Total Time, reference tfdist_between.py:98-110) — kept as-is in
``utils/logging.py``. This module adds the TPU-native upgrade the survey
prescribes: ``jax.profiler`` traces (XLA op-level timelines viewable in
TensorBoard/Perfetto) and an on-demand profiling server.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace for the enclosed block::

        with profiler.trace("./logs/profile"):
            state, cost = train_step(state, x, y)
            float(cost)  # D2H fetch: the trustworthy barrier (utils/sync.py)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the on-demand profiling server (connect with TensorBoard's
    profile tab or `xprof`); returns the server object."""
    return jax.profiler.start_server(port)


def annotate(name: str):
    """Named region that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)
