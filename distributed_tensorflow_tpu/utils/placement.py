"""Placement verification (C4's ``log_device_placement`` analog).

The reference verified placement by turning on
``log_device_placement=True`` and eyeballing that ops landed on
``/job:worker/task:N/gpu:N`` (reference tfdist_between.py:15, SURVEY.md §4.3).
On TPU there are no device strings: placement *is* sharding. This module
renders the sharding of every leaf in a pytree — which mesh axes each dim is
split over and which devices hold shards — for the same eyeball check.
"""

from __future__ import annotations

import jax


def describe(tree, *, print_fn=print) -> list[str]:
    """Print (and return) one line per array leaf: path, shape, sharding
    spec, and the number of devices holding shards."""
    lines = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = jax.tree_util.keystr(path)
        if not hasattr(leaf, "sharding"):
            lines.append(f"{name}: (non-array) {type(leaf).__name__}")
            continue
        sh = leaf.sharding
        spec = getattr(sh, "spec", sh)
        ndev = len(getattr(sh, "device_set", [None]))
        lines.append(
            f"{name}: shape={tuple(leaf.shape)} dtype={leaf.dtype} "
            f"spec={spec} devices={ndev}"
        )
    for line in lines:
        print_fn(line)
    return lines


def assert_replicated(tree) -> None:
    """Assert every leaf is fully replicated (pure-DP invariant)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if hasattr(leaf, "sharding") and not leaf.sharding.is_fully_replicated:
            raise AssertionError(
                f"{jax.tree_util.keystr(path)} is not replicated: "
                f"{leaf.sharding}"
            )


def assert_sharded_over(tree, axis: str) -> None:
    """Assert at least one leaf is actually split over mesh axis ``axis``
    (guards against silently-replicated 'sharded' runs)."""
    for _, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not hasattr(leaf, "sharding"):
            continue
        spec = getattr(leaf.sharding, "spec", ())
        for entry in spec:
            names = entry if isinstance(entry, tuple) else (entry,)
            if axis in names:
                return
    raise AssertionError(f"no leaf is sharded over axis {axis!r}")
