"""Scalar summaries / TensorBoard event files (component C15, SURVEY.md §2).

The reference writes ``tf.summary.scalar("cost"/"accuracy")`` through a
``FileWriter('./logs')`` every batch (reference tfsingle.py:55-57,69,81).
This framework has no TensorFlow dependency, so the ``tfevents`` wire format
is implemented directly: TFRecord framing (length + masked CRC32C) around
hand-encoded ``Event``/``Summary`` protobuf messages. TensorBoard reads the
resulting files natively.

Only the pieces the reference uses are implemented: scalar values keyed by
tag, the file-version header record, and — matching the reference's
``FileWriter('./logs', graph=tf.get_default_graph())`` (reference
tfsingle.py:69, tfdist_between.py:83-84) — a graph dump. There is no TF
graph here, so the dumped graph is the *jaxpr* of the compiled train step,
encoded as a ``GraphDef`` (one NodeDef per equation, sub-jaxprs nested via
``/``-scoped names) that TensorBoard's Graphs tab renders natively.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven — required by the TFRecord framing.
# ---------------------------------------------------------------------------

_CRC_TABLE: list[int] = []


def _build_table() -> None:
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc_py(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """TFRecord-masked CRC32C. Uses the native runtime when available
    (runtime/csrc/dtf_runtime.cc — the reference's FileWriter computed this
    inside TF's C++ core); the pure-Python table otherwise. The per-record
    checksum runs twice per batch for 55k batches, so the native path
    matters on the eager loop."""
    global _masked_crc_impl
    if _masked_crc_impl is None:
        try:
            from distributed_tensorflow_tpu.runtime.native import crc32c_masked

            crc32c_masked(b"probe")  # force library load now
            _masked_crc_impl = crc32c_masked
        except (ImportError, OSError):
            _masked_crc_impl = _masked_crc_py
    return _masked_crc_impl(data)


_masked_crc_impl = None


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format encoders (only what Event/Summary need).
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field_varint(field: int, value: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(value)


def _field_double(field: int, value: float) -> bytes:
    return _varint(field << 3 | 1) + struct.pack("<d", value)


def _field_float(field: int, value: float) -> bytes:
    return _varint(field << 3 | 5) + struct.pack("<f", value)


def _field_bytes(field: int, value: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(value)) + value


def _encode_scalar_event(wall_time: float, step: int, tag: str, value: float) -> bytes:
    # Summary.Value { string tag = 1; float simple_value = 2; }
    sval = _field_bytes(1, tag.encode()) + _field_float(2, value)
    # Summary { repeated Value value = 1; }
    summary = _field_bytes(1, sval)
    # Event { double wall_time = 1; int64 step = 2; Summary summary = 5; }
    return _field_double(1, wall_time) + _field_varint(2, step) + _field_bytes(5, summary)


def _encode_version_event(wall_time: float) -> bytes:
    # Event { double wall_time = 1; string file_version = 3; }
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


# ---------------------------------------------------------------------------
# jaxpr → GraphDef (the reference's graph dump, C15).
# ---------------------------------------------------------------------------

_NAME_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_.-/")


def _sanitize(name: str) -> str:
    return "".join(c if c in _NAME_OK else "_" for c in name) or "node"


def _attr_s(key: str, value: str) -> bytes:
    # map<string, AttrValue> entry { key = 1; AttrValue value = 2; }
    # AttrValue { bytes s = 2; }
    attr_value = _field_bytes(2, value.encode())
    return _field_bytes(5, _field_bytes(1, key.encode()) + _field_bytes(2, attr_value))


def _node_def(name: str, op: str, inputs: list[str], attrs: dict[str, str]) -> bytes:
    # NodeDef { string name = 1; string op = 2; repeated string input = 3;
    #           map<string, AttrValue> attr = 5; }
    out = _field_bytes(1, name.encode()) + _field_bytes(2, op.encode())
    for i in inputs:
        out += _field_bytes(3, i.encode())
    for k, v in attrs.items():
        out += _attr_s(k, v)
    return out


def _aval_str(v) -> str:
    aval = getattr(v, "aval", None)
    if aval is None:
        return ""
    return "%s%s" % (getattr(aval, "dtype", "?"), list(getattr(aval, "shape", ())))


class _GraphBuilder:
    """Flattens a (possibly nested) jaxpr into GraphDef nodes.

    Each equation becomes one node named ``<scope><primitive>_<k>``; eqn
    params that are themselves jaxprs (pjit, scan, while, cond branches, ...)
    are emitted under that node's name as a ``/`` scope, which TensorBoard
    collapses into an expandable group. Sub-jaxpr inputs are wired to the
    outer equation's input nodes positionally where lengths allow (scan
    reorders consts/carries; edges inside a scope remain exact).
    """

    def __init__(self):
        self.nodes: list[bytes] = []
        self.env: dict = {}  # Var -> producing node name
        self.counter = 0

    def _fresh(self, scope: str, op: str) -> str:
        self.counter += 1
        return _sanitize("%s%s_%d" % (scope, op, self.counter))

    def _input_name(self, v, scope: str) -> str:
        from jax.extend import core as jex_core

        if isinstance(v, jex_core.Literal):
            name = self._fresh(scope, "Const")
            self.nodes.append(
                _node_def(name, "Const", [], {"value": str(v.val), "output": _aval_str(v)})
            )
            return name
        if v not in self.env:
            # Unbound within this scope (e.g. scan-reordered sub-jaxpr input).
            name = self._fresh(scope, "capture")
            self.nodes.append(_node_def(name, "Capture", [], {"output": _aval_str(v)}))
            self.env[v] = name
        return self.env[v]

    def add_jaxpr(self, jaxpr, scope: str = "", input_names: list[str] | None = None):
        from jax.extend import core as jex_core

        if isinstance(jaxpr, jex_core.ClosedJaxpr):
            jaxpr = jaxpr.jaxpr
        for i, v in enumerate(jaxpr.invars):
            if input_names is not None and i < len(input_names):
                self.env[v] = input_names[i]
            elif v not in self.env:
                name = _sanitize("%sinput_%d" % (scope, i))
                self.nodes.append(
                    _node_def(name, "Placeholder", [], {"output": _aval_str(v)})
                )
                self.env[v] = name
        for v in jaxpr.constvars:
            if v not in self.env:
                name = self._fresh(scope, "Const")
                self.nodes.append(_node_def(name, "Const", [], {"output": _aval_str(v)}))
                self.env[v] = name
        for eqn in jaxpr.eqns:
            op = eqn.primitive.name
            inputs = [self._input_name(v, scope) for v in eqn.invars]
            name = self._fresh(scope, op)
            attrs = {}
            if eqn.outvars:
                attrs["output"] = _aval_str(eqn.outvars[0])
            self.nodes.append(_node_def(name, op, inputs, attrs))
            for v in eqn.outvars:
                # DropVars are unique per site, so binding them is harmless.
                self.env[v] = name
            # Nest sub-jaxprs (pjit/scan/while/cond/custom_vjp ...) as a scope.
            subs = []
            for key, val in eqn.params.items():
                if isinstance(val, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
                    subs.append((key, val))
                elif isinstance(val, (tuple, list)) and val and all(
                    isinstance(x, (jex_core.Jaxpr, jex_core.ClosedJaxpr)) for x in val
                ):
                    subs.extend(("%s_%d" % (key, j), x) for j, x in enumerate(val))
            for key, sub in subs:
                sub_scope = "%s/%s/" % (name, key) if len(subs) > 1 else name + "/"
                self.add_jaxpr(sub, scope=sub_scope, input_names=inputs)

    def graph_def(self) -> bytes:
        # GraphDef { repeated NodeDef node = 1; VersionDef versions = 4; }
        # VersionDef { int32 producer = 1; }
        out = b"".join(_field_bytes(1, n) for n in self.nodes)
        out += _field_bytes(4, _field_varint(1, 22))
        return out


def graph_def_from_fn(fn, *example_args) -> bytes:
    """Serialized GraphDef of ``jax.make_jaxpr(fn)(*example_args)``."""
    import jax

    closed = jax.make_jaxpr(fn)(*example_args)
    b = _GraphBuilder()
    b.add_jaxpr(closed)
    return b.graph_def()


def _encode_graph_event(wall_time: float, graph_def: bytes) -> bytes:
    # Event { double wall_time = 1; bytes graph_def = 4; }
    return _field_double(1, wall_time) + _field_bytes(4, graph_def)


class SummaryWriter:
    """Drop-in for the reference's ``FileWriter('./logs')`` scalar usage."""

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        fname = "events.out.tfevents.%010d.%s%s" % (
            int(time.time()),
            socket.gethostname(),
            filename_suffix,
        )
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "ab")
        self._write_record(_encode_version_event(time.time()))

    @property
    def path(self) -> str:
        return self._path

    def _write_record(self, data: bytes) -> None:
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(
            _encode_scalar_event(time.time(), int(step), tag, float(value))
        )

    def add_scalars(self, scalars: dict[str, float], step: int) -> None:
        for tag, value in scalars.items():
            self.add_scalar(tag, value, step)

    def add_graph(self, fn, *example_args) -> None:
        """Dump ``fn``'s jaxpr as a TensorBoard graph (reference
        tfsingle.py:69 passed the TF graph to the FileWriter)."""
        self._write_record(
            _encode_graph_event(time.time(), graph_def_from_fn(fn, *example_args))
        )

    def flush(self) -> None:
        # fsync, not just flush: the resilience contract (docs/resilience.md)
        # flushes at run end and after rollback/preemption events — those
        # records must survive the process being killed right after.
        self._f.flush()
        try:
            os.fsync(self._f.fileno())
        except OSError:  # pragma: no cover — exotic filesystems
            pass

    def close(self) -> None:
        self.flush()
        self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Lifecycle emitter (round 10): ONE call site contract for the resilience/
# elasticity signals. Before this, the restart/resize/rollback/world_size
# scalars were each hand-wired at their call sites next to a hand-built
# structured print — four copies of the same three-way fan-out (stdout line
# + tfevents scalar + now the journal event) drifting independently. The
# line wording lives in observability/format.py (grep-lint-enforced); this
# helper owns the fan-out.
# ---------------------------------------------------------------------------


def lifecycle_event(
    kind: str,
    *,
    print_fn=None,
    journal=None,
    writer: "SummaryWriter | None" = None,
    scalar: tuple | None = None,
    **fields,
) -> dict:
    """Emit one lifecycle signal everywhere it belongs:

    - a typed journal event (``observability.format.emit_line``; the
      process-default :class:`~observability.journal.NullJournal` when no
      journal is attached),
    - the structured stdout line rendered FROM that event (byte-identical
      to the pre-journal wording) via ``print_fn``,
    - and, when ``writer`` and ``scalar=(tag, value, step)`` are given,
      the tfevents scalar the TensorBoard surface keeps showing.

    Returns the event dict. tests/test_observability.py asserts each
    lifecycle kind lands in BOTH tfevents and the journal through here.
    """
    from distributed_tensorflow_tpu.observability import format as obs_format

    ev = obs_format.emit_line(
        kind, journal=journal, print_fn=print_fn, **fields
    )
    if writer is not None and scalar is not None:
        tag, value, step = scalar
        writer.add_scalar(tag, float(value), int(step))
    return ev
