"""Scalar summaries / TensorBoard event files (component C15, SURVEY.md §2).

The reference writes ``tf.summary.scalar("cost"/"accuracy")`` through a
``FileWriter('./logs')`` every batch (reference tfsingle.py:55-57,69,81).
This framework has no TensorFlow dependency, so the ``tfevents`` wire format
is implemented directly: TFRecord framing (length + masked CRC32C) around
hand-encoded ``Event``/``Summary`` protobuf messages. TensorBoard reads the
resulting files natively.

Only the pieces the reference uses are implemented: scalar values keyed by
tag, plus the file-version header record.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven — required by the TFRecord framing.
# ---------------------------------------------------------------------------

_CRC_TABLE: list[int] = []


def _build_table() -> None:
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format encoders (only what Event/Summary need).
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field_varint(field: int, value: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(value)


def _field_double(field: int, value: float) -> bytes:
    return _varint(field << 3 | 1) + struct.pack("<d", value)


def _field_float(field: int, value: float) -> bytes:
    return _varint(field << 3 | 5) + struct.pack("<f", value)


def _field_bytes(field: int, value: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(value)) + value


def _encode_scalar_event(wall_time: float, step: int, tag: str, value: float) -> bytes:
    # Summary.Value { string tag = 1; float simple_value = 2; }
    sval = _field_bytes(1, tag.encode()) + _field_float(2, value)
    # Summary { repeated Value value = 1; }
    summary = _field_bytes(1, sval)
    # Event { double wall_time = 1; int64 step = 2; Summary summary = 5; }
    return _field_double(1, wall_time) + _field_varint(2, step) + _field_bytes(5, summary)


def _encode_version_event(wall_time: float) -> bytes:
    # Event { double wall_time = 1; string file_version = 3; }
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


class SummaryWriter:
    """Drop-in for the reference's ``FileWriter('./logs')`` scalar usage."""

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        fname = "events.out.tfevents.%010d.%s%s" % (
            int(time.time()),
            socket.gethostname(),
            filename_suffix,
        )
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "ab")
        self._write_record(_encode_version_event(time.time()))

    @property
    def path(self) -> str:
        return self._path

    def _write_record(self, data: bytes) -> None:
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(
            _encode_scalar_event(time.time(), int(step), tag, float(value))
        )

    def add_scalars(self, scalars: dict[str, float], step: int) -> None:
        for tag, value in scalars.items():
            self.add_scalar(tag, value, step)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.flush()
        self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
