"""Scanned multi-step training: many SGD steps per device dispatch.

The reference pays one Python→runtime round trip per 100-example batch
(``sess.run`` per batch, reference tfsingle.py:78-80) — on its hardware that
cost 1.3 s/epoch; on a dispatch-latency-bound link it is catastrophic. The
TPU-first design instead compiles K steps into one XLA program with
``lax.scan``: the full epoch's batches are staged in HBM once (MNIST is
~86 MB in bf16 — trivially resident), the scan walks batch slices on-device,
and the host syncs once per dispatch. Per-step overhead drops to zero and
XLA can overlap the data slicing with MXU work.

This is the path ``bench.py`` measures and the path to use whenever the
per-step host round trip (logging every batch) is not needed. The semantics
are bit-identical to the eager loop: same batches, same order, same updates.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributed_tensorflow_tpu.parallel.strategy import TrainState, _loss_from_model


def make_scanned_train_fn(
    model,
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    batch_sharding=None,
    donate: bool = True,
) -> Callable:
    """Build ``fn(state, xs, ys) -> (state, costs)`` where ``xs`` has shape
    [num_steps, batch, features]: one compiled dispatch running every step.

    With ``batch_sharding`` (a NamedSharding over the ``data`` axis on dim 1
    of each scan slice), the same program is sync data-parallel: each scan
    iteration's batch is sharded across chips and GSPMD inserts the gradient
    all-reduce — ``SyncReplicasOptimizer`` at zero dispatch cost.
    """

    def step(state: TrainState, batch):
        x, y = batch
        if batch_sharding is not None:
            x = jax.lax.with_sharding_constraint(x, batch_sharding)
            y = jax.lax.with_sharding_constraint(y, batch_sharding)
        cost, grads = jax.value_and_grad(partial(_loss_from_model, model, loss_fn))(
            state.params, x, y
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), cost

    @partial(jax.jit, donate_argnums=0 if donate else ())
    def run(state: TrainState, xs: jax.Array, ys: jax.Array):
        return jax.lax.scan(step, state, (xs, ys))

    return run


def make_indexed_scanned_train_fn(
    model,
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    batch_sharding=None,
    donate: bool = True,
) -> Callable:
    """Build ``fn(state, train_x, train_y, idxs) -> (state, costs)`` where
    ``train_x``/``train_y`` are the FULL flat training arrays (device-resident,
    staged once for the whole run) and ``idxs`` is ``[steps, batch]`` int32 row
    indices — the only per-epoch upload. Each scan iteration gathers its batch
    on-device, so re-shuffling an epoch costs a ~0.2 MB index transfer instead
    of re-staging ~170 MB of batches through the host link (the round-1
    Trainer-on-TPU gap: the tunnel made per-epoch restaging cost more than the
    epoch's compute). Same update semantics as ``make_scanned_train_fn`` over
    ``stage_epoch`` output for the same permutation."""

    def step_fn(train_x, train_y):
        def step(state: TrainState, idx):
            x = jnp.take(train_x, idx, axis=0)
            y = jnp.take(train_y, idx, axis=0)
            if batch_sharding is not None:
                x = jax.lax.with_sharding_constraint(x, batch_sharding)
                y = jax.lax.with_sharding_constraint(y, batch_sharding)
            cost, grads = jax.value_and_grad(
                partial(_loss_from_model, model, loss_fn)
            )(state.params, x, y)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1), cost

        return step

    @partial(jax.jit, donate_argnums=0 if donate else ())
    def run(state: TrainState, train_x, train_y, idxs):
        return jax.lax.scan(step_fn(train_x, train_y), state, idxs)

    return run


def stage_epoch(
    images, labels, batch_size: int, *, rng=None, dtype=jnp.float32
):
    """Shape one epoch of host data into [steps, batch, ...] scan slices
    (shuffled like ``DataSet.next_batch``), ready for a single device_put."""
    import numpy as np

    n = (images.shape[0] // batch_size) * batch_size
    perm = (
        rng.permutation(images.shape[0])[:n]
        if rng is not None
        else np.arange(n)
    )
    xs = images[perm].reshape(-1, batch_size, images.shape[1]).astype(dtype)
    ys = labels[perm].reshape(-1, batch_size, labels.shape[1]).astype(dtype)
    return xs, ys
