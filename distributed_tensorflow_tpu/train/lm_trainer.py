"""LM training lifecycle — the reference loop contract over token batches.

Round 2 left the GPT family training through bare step factories and a
hand-rolled loop (VERDICT round-2 missing #2); this module applies the full
reference contract (reference tfdist_between.py:86-111) to the LM family,
exactly as ``train/trainer.py`` does for the classifiers:

- epochs × ``num_train // batch_size`` steps over a
  :class:`~data.tokens.TokenDataset` (``next_batch`` semantics, C6);
- ``Step/Epoch/Batch/Cost/AvgTime`` lines at ``log_frequency`` cadence and
  a per-epoch held-out metric — **perplexity** (exp mean next-token CE),
  the LM's analog of the reference's per-epoch ``Test-Accuracy``
  (reference tfdist_between.py:101-110);
- scalar summaries (``cost`` per step, ``perplexity`` per epoch) through
  the same dependency-free tfevents writer (C15);
- Supervisor checkpointing: restore-or-init at construction, save per
  epoch, heartbeat-reactive stop (C13);
- a **scanned-epoch fast path** (default on accelerators, like the
  classifier Trainer): token data staged device-resident once, one
  ``lax.scan`` dispatch per epoch gathering batches on device from an
  uploaded [steps, batch] index permutation — drawn from the SAME
  ``next_indices`` stream as the eager loop, so the two paths see
  identical batch sequences.

Data-parallel: pass ``mesh`` — the eager path uses
``make_lm_train_step(mesh=...)`` (shard_map + pmean), the scanned path
shards each gathered batch over ``data`` via a sharding constraint and
lets GSPMD insert the gradient all-reduce; both equal the single-device
math on the global batch. Ragged corpora (datasets with ``lengths``) train
through the masked loss end to end.

**Mode matrix** (round 4 — the same selection surface the classifier
Trainer gets from ``TrainConfig``; the reference picked its mode by
picking which script to launch, reference README.md:90-121):

- ``mesh=None`` → **single** device;
- ``mesh`` + ``config.sync=True`` + ``dp_mode="replicated"`` → **dp**
  (gradient all-reduce, the reference's sync mode);
- ``mesh`` + ``config.sync=True`` + ``dp_mode="zero"`` → **zero**
  (ZeRO: params AND optimizer slots sharded over ``data`` via
  ``parallel/fsdp.fsdp_specs``, all-gather fwd/bwd + reduce-scatter
  grads — identical update semantics to dp);
- ``mesh`` + ``config.sync=False`` → **async** local-SGD
  (``models/gpt.make_lm_async_parts``: per-device parameter copies,
  exchange to the mean every ``config.async_avg_every`` steps, the
  reference's HOGWILD table emulated as in ``AsyncDataParallel``;
  held-out perplexity is evaluated at the mean of the copies, and
  ``update_scale`` defaults to N like every async API here);
- ``mesh`` + ``dp_mode="tp"`` → **tp** (Megatron tensor parallelism over
  ``tp_axis`` via ``GPTLM.partition_specs``, params AND optimizer slots
  column/row-sharded, ONE GSPMD program; composes with a ``data`` axis
  on the same mesh → dp×tp, identical math to the single-device step);
- ``mesh`` + ``dp_mode="ep"`` → **ep** (MoE models: expert-parallel
  all-to-all training over ``expert_axis`` via
  ``models/gpt.make_lm_ep_parts`` — one expert's FFN weights + slots per
  device; composes with a ``data`` axis → dp×ep; ragged corpora mask
  routing per shard);
- ``mesh`` + ``dp_mode="pp"`` → **pp** (GPipe pipeline training over
  ``stage_axis`` via ``models/gpt.make_lm_pp_parts`` — stage-owned layer
  groups + slots, backward as the tick-scan transpose; composes with a
  ``data`` axis → dp×pp; ``pp_microbatches`` microbatches);
- ``mesh`` + ``dp_mode="sp"`` → **sp** (sequence-parallel training over
  ``seq_axis`` via ``models/gpt.make_lm_sp_parts`` — L/n tokens of
  activations per device, KV on the causal ring (or Ulysses all-to-all,
  ``sp_attention=``), the EXACT global masked CE assembled from psum'd
  shard sums with the boundary target over one ppermute hop; params
  replicated; composes with a ``data`` axis → dp×sp);
- ``dp_mode="diloco"`` → **diloco** (round 14: local-SGD/DiLoCo outer
  loop, ``train/local_sgd.py`` — per-worker copies run
  ``config.sync_every`` = H inner steps each, then ONE outer
  Nesterov-momentum update from the pseudo-gradient
  Δ = θ_start − mean_w(θ_w): H× fewer all-reduce rounds per token than
  dp, the paper's async-over-sync thesis in its communication-reducing
  modern form. Gang = the ``data`` mesh axis, or — with no mesh —
  ``config.diloco_workers`` emulated workers vmapped into one
  single-device program (same math, bench/degraded-container engine).
  Outer state (θ_start anchor + momentum buffer) lives in the
  optimizer-state slot as a ``DiLoCoState`` and is world-size-invariant,
  so an elastic resize carries it across a world change).

Every mode runs the FULL lifecycle: log lines, per-epoch perplexity,
tfevents, Supervisor save/restore (async checkpoints the stacked copies;
zero/tp/ep/pp checkpoint sharded arrays — pp in the staged layout; sp
params are replicated), the scanned epoch, and run_compiled. Held-out
perplexity is defined at the model's dense forward everywhere (async
folds the copies to their mean; pp merges the staged layer groups back;
ep reads the dense forward, == the EP forward in the no-drop regime —
``drop_fraction`` is the guard; sp == dense exactly).
"""

from __future__ import annotations

import copy
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.models.gpt import GPTLM, make_lm_train_step
from distributed_tensorflow_tpu.observability import journal as obs_journal
from distributed_tensorflow_tpu.observability.metrics import MetricsRegistry
from distributed_tensorflow_tpu.observability.spans import SpanRecorder
from distributed_tensorflow_tpu.ops import optim as optim_lib
from distributed_tensorflow_tpu.parallel.strategy import TrainState
from distributed_tensorflow_tpu.train.supervisor import Supervisor
from distributed_tensorflow_tpu.utils.logging import StepLogger
from distributed_tensorflow_tpu.utils.summary import SummaryWriter, lifecycle_event


class LMTrainer:
    def __init__(
        self,
        model: GPTLM,
        datasets,
        config: TrainConfig | None = None,
        *,
        optimizer=None,
        mesh=None,
        data_axis: str = "data",
        summary_writer: SummaryWriter | None = None,
        supervisor: Supervisor | None = None,
        is_chief: bool = True,
        eval_batch: int = 256,
        print_fn=print,
        async_update_scale: float | None = None,
        tp_axis: str = "model",
        expert_axis: str = "expert",
        stage_axis: str = "stage",
        pp_microbatches: int = 4,
        seq_axis: str = "seq",
        sp_attention: str | None = None,
        tokenizer=None,
        journal=None,
        metrics: MetricsRegistry | None = None,
        delta_exchange=None,
    ):
        self.datasets = datasets
        self.config = config or TrainConfig()
        # Config-driven perf knobs (round 13): TrainConfig is the single
        # config surface (config_from_env deployments), so a remat policy
        # (True | "selective") or low-precision matmul request set there
        # lands on the model — every dp_mode routes through the model's
        # forward, which is what makes the knob reach all of them. A knob
        # the caller already set on the model itself wins on conflict
        # (TrainConfig validates its values in __post_init__). The knobs
        # land on a trainer-local SHALLOW COPY: mutating the caller's
        # instance would leak one trainer's config into every other user
        # of the same model object (a second trainer, an eval harness).
        apply_remat = self.config.remat and not model.remat
        apply_mm = self.config.matmul_dtype and model.matmul_dtype is None
        if apply_remat or apply_mm:
            model = copy.copy(model)
            if apply_remat:
                model.remat = self.config.remat
            if apply_mm:
                model.matmul_dtype = self.config.matmul_dtype
        self.model = model
        self.optimizer = optimizer or optim_lib.make(
            self.config.optimizer, self.config.learning_rate
        )
        self.mesh = mesh
        self.data_axis = data_axis
        self.summary_writer = summary_writer
        self.is_chief = is_chief
        self.eval_batch = eval_batch
        self.print_fn = print_fn
        self.async_update_scale = async_update_scale
        self.tp_axis = tp_axis
        self.expert_axis = expert_axis
        self.stage_axis = stage_axis
        self.pp_microbatches = pp_microbatches
        self.seq_axis = seq_axis
        self.sp_attention = sp_attention
        # Telemetry (round 10, observability/): journal defaults to the
        # process-wide one (no-op NullJournal unless configured); the
        # structured lines below render FROM journal events.
        self.journal = journal if journal is not None else obs_journal.get_journal()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.spans = SpanRecorder(journal=self.journal)
        self._ragged = datasets.train.lengths is not None
        # Stale-tolerant mailbox gang (round 17, local_sgd.DeltaExchange):
        # one member per process, outer rounds exchanged host-side with
        # staleness-weighted peer deltas. The exchange's own knobs must
        # agree with the config's (config_from_env is the single config
        # surface — a drifted pair would compress with one dtype and
        # decode with another).
        self.delta_exchange = delta_exchange
        if delta_exchange is not None:
            if delta_exchange.delta_dtype != self.config.delta_dtype:
                raise ValueError(
                    f"delta_exchange.delta_dtype="
                    f"{delta_exchange.delta_dtype!r} disagrees with "
                    f"config.delta_dtype={self.config.delta_dtype!r}"
                )
            if delta_exchange.stale_limit != self.config.stale_limit:
                raise ValueError(
                    f"delta_exchange.stale_limit="
                    f"{delta_exchange.stale_limit} disagrees with "
                    f"config.stale_limit={self.config.stale_limit}"
                )
            # The exchange's mailbox_corrupt events (round 19) ride this
            # trainer's journal unless the caller wired its own; same for
            # the corruption counter (round 21 — exporter-visible).
            if getattr(delta_exchange, "journal", None) is None:
                delta_exchange.journal = self.journal
            if getattr(delta_exchange, "metrics", None) is None:
                delta_exchange.metrics = self.metrics
        self.mode = self._resolve_mode()

        self.state = self._init_state(model.init(seed=self.config.seed))
        self._eager_step = None  # built lazily (scanned path may not need it)
        self._scanned_fn = None
        self._eval_chunk = None
        self._stage_cache: dict = {}

        self.supervisor = supervisor
        if self.supervisor is None and self.config.checkpoint_dir:
            self.supervisor = Supervisor(
                is_chief=is_chief,
                checkpoint_dir=self.config.checkpoint_dir,
                keep_last_n=self.config.keep_last_n,
                io_retries=self.config.checkpoint_retries,
                io_backoff=self.config.checkpoint_retry_backoff,
                async_checkpoint=self.config.async_checkpoint,
            )
        self.tokenizer = tokenizer
        if (
            tokenizer is not None
            and self.supervisor is not None
            and self.supervisor.checkpoint_dir
            and self.supervisor.is_chief
            and hasattr(tokenizer, "save")
        ):
            # The vocab ships WITH the checkpoint: a restored model is
            # useless without the exact merges that produced its token ids
            # (reference analog: none — its data pipeline had no learned
            # state; this is part of the deliberate checkpoint upgrade).
            # Supervisor only creates the directory when orbax is present,
            # so make sure it exists before writing the vocab.
            os.makedirs(self.supervisor.checkpoint_dir, exist_ok=True)
            self._write_tokenizer(tokenizer)
        self.start_step = 0
        if self.supervisor is not None:
            self.supervisor.attach_observability(
                self.journal, self.metrics, self.spans
            )
            # Newest step that is not known-corrupt (manifest-verified,
            # train/resilience.py): a truncated latest checkpoint points
            # the restore at the previous valid one.
            step = self.supervisor.newest_restorable_step()
            src = (
                self.supervisor.saved_layout(step)
                if step is not None
                else None
            )
            if step is not None and src is not None and not (
                self._layout_compatible(src)
            ):
                # Cross-topology restore (round 5): the checkpoint was
                # written by a DIFFERENT mode layout (pp's staged blocks,
                # async's stacked copies, or a different stage/replica
                # count). Restore it in ITS shapes, canonicalize to the
                # dense single-device layout, then re-stage into this
                # trainer's layout — elasticity the reference's
                # Supervisor (topology-pinned re-attach) never had.
                raw = self.supervisor.restore_raw(
                    step, self._abstract_state_for(src)
                )
                restored = self._state_from_canonical(
                    self._state_to_canonical(raw, src)
                )
                if src.get("mode") == "diloco" and self.mode == "diloco":
                    # Elastic resize within the diloco family: the outer
                    # state (θ_start anchor + Nesterov momentum) carries
                    # DENSE shapes, so it survives the world change
                    # verbatim — the next outer round's pseudo-gradient
                    # is computed against the SAVED anchor over the
                    # survivor gang ("the outer update proceeds over
                    # survivors", docs/parallelism.md §local-SGD). The
                    # round-17 lever state (EF residual, in-flight
                    # delta) is world-invariant too and carries the same
                    # way — when both sides run the lever; a lever
                    # flipped across the resize keeps the target's fresh
                    # zeros (residual) / drops the saved one (the
                    # compression error it deferred is lost once, not
                    # corrupted).
                    carry = dict(
                        theta=raw.opt_state.theta,
                        momentum=raw.opt_state.momentum,
                    )
                    if self.config.delta_dtype and src.get(
                        "delta_dtype"
                    ) == self.config.delta_dtype:
                        carry["residual"] = raw.opt_state.residual
                    if self.config.delta_overlap and src.get("overlap"):
                        carry["inflight"] = raw.opt_state.inflight
                    restored = restored._replace(
                        opt_state=restored.opt_state._replace(**carry)
                    )
                self.state = self._place_state(restored)
                self.start_step = step
            else:
                # verified_step: the probe above already CRC-verified this
                # step's files — skip the redundant disk re-read.
                self.state, self.start_step = (
                    self.supervisor.prepare_or_restore(
                        self.state, verified_step=step
                    )
                )
                self.state = self._place_state(self.state)
            # Global-batch policy across an elastic resize (round 8,
            # docs/resilience.md): the LM batch_size IS the global batch,
            # so a world-size change needs no adoption — each shard just
            # grows — but the CONFIG must carry the same value, or the
            # step→data-stream mapping (and the trajectory) silently
            # changes. Asserted, not adopted: the divisibility checks in
            # _resolve_mode already ran against config.batch_size.
            if src is not None and src.get("global_batch") is not None:
                saved_gb = int(src["global_batch"])
                if saved_gb != int(self.config.batch_size):
                    raise ValueError(
                        f"checkpoint was trained with global batch "
                        f"{saved_gb} (world={src.get('world')}) but this "
                        f"config says batch_size={self.config.batch_size}"
                        "; the LM batch is GLOBAL — resume with the same "
                        "batch_size (the per-shard batch grows with the "
                        "smaller mesh) to preserve the trajectory and "
                        "data-stream position"
                    )
            # Fast-forward the host-side index stream so a resumed run
            # draws exactly the batches the uninterrupted run would (the
            # reference resumed against live PS state; the TPU-native
            # analog restores the state pytree and replays the
            # deterministic data stream up to it — proven bitwise in
            # test_lm_trainer.py::test_supervisor_resume_bitwise; the
            # draw is world-invariant because batch_size is global, so
            # the position is preserved across a resize too).
            for _ in range(self.start_step):
                datasets.train.next_indices(self.config.batch_size)

        scan_epoch = self.config.scan_epoch
        if scan_epoch is None:
            # Same backend default as the classifier Trainer: on an
            # accelerator the per-batch eager loop pays the device-link
            # dispatch latency per step (CLAUDE.md); scan the epoch.
            scan_epoch = jax.default_backend() != "cpu"
        self._scan = bool(scan_epoch)
        if self.delta_exchange is not None:
            # The mailbox round is a HOST decision point every
            # sync_every steps (post + gather + apply) — it cannot ride
            # inside a scanned-epoch dispatch.
            self._scan = False

        self.last_cost = None
        self._epoch_costs = None  # per-step costs of the last scanned epoch
        self.history: list[dict] = []

    def _write_tokenizer(self, tokenizer) -> None:
        """Write ``tokenizer.json`` into checkpoint_dir — unless one is
        already there. An existing record is the vocab that produced the
        CHECKPOINT's token ids: matching merges make the write a no-op,
        mismatched merges refuse loudly instead of silently replacing the
        record the restored weights depend on (ADVICE round 5)."""
        path = os.path.join(self.supervisor.checkpoint_dir, "tokenizer.json")
        if os.path.exists(path):
            from distributed_tensorflow_tpu.data.text import BPETokenizer

            try:
                existing = BPETokenizer.load(path)
            except Exception as exc:
                raise ValueError(
                    f"checkpoint_dir already holds an unreadable {path} "
                    f"({type(exc).__name__}: {exc}); refusing to overwrite "
                    "the vocab record the checkpoint's token ids depend on"
                ) from exc
            if getattr(tokenizer, "merges", None) != existing.merges:
                raise ValueError(
                    f"tokenizer mismatch: {path} holds "
                    f"{len(existing.merges)} merges that differ from this "
                    f"tokenizer's {len(getattr(tokenizer, 'merges', []))}; "
                    "refusing to overwrite the vocab that matches the "
                    "checkpoint's token ids (use a fresh checkpoint_dir "
                    "to train with a new vocab)"
                )
            return  # identical vocab: nothing to do
        tokenizer.save(path)

    # -- modes -------------------------------------------------------------

    def _resolve_mode(self) -> str:
        cfg = self.config
        if cfg.dp_mode not in (
            "replicated", "zero", "tp", "ep", "pp", "sp", "diloco"
        ):
            raise ValueError(
                f"unknown dp_mode {cfg.dp_mode!r}; "
                "replicated|zero|tp|ep|pp|sp|diloco"
            )
        if cfg.dp_mode != "diloco" and (
            self.delta_exchange is not None
        ):
            raise ValueError(
                "delta_exchange is the diloco mailbox gang: it requires "
                f"dp_mode='diloco', got {cfg.dp_mode!r}"
            )
        if cfg.dp_mode == "diloco":
            if not cfg.sync:
                raise ValueError(
                    "dp_mode='diloco' does not compose with sync=False: "
                    "the outer loop IS the (reduced) synchronization; "
                    "use sync=False + async_avg_every for the HOGWILD "
                    "emulation instead"
                )
            if self.delta_exchange is not None:
                # Mailbox gang: one member per PROCESS — the gang is the
                # set of processes sharing the exchange directory, not a
                # mesh axis or an in-process emulation.
                if self.mesh is not None:
                    raise ValueError(
                        "delta_exchange runs one gang member per process "
                        "(the outer round is a host decision point): "
                        "pass mesh=None with diloco_workers=1"
                    )
                if cfg.diloco_workers != 1:
                    raise ValueError(
                        "delta_exchange needs diloco_workers=1 (each "
                        f"process is ONE member), got {cfg.diloco_workers}"
                    )
                if cfg.delta_overlap:
                    raise ValueError(
                        "delta_overlap does not compose with "
                        "delta_exchange: the mailbox gang never waits on "
                        "the exchange — staleness tolerance IS its "
                        "overlap"
                    )
                if cfg.epochs_per_dispatch:
                    raise ValueError(
                        "epochs_per_dispatch does not compose with "
                        "delta_exchange: the outer round is a host "
                        "decision point inside every epoch"
                    )
                return "diloco"
            if self.mesh is not None:
                if self.data_axis not in self.mesh.shape:
                    raise ValueError(
                        f"dp_mode='diloco' needs a {self.data_axis!r} "
                        f"mesh axis (the gang): {dict(self.mesh.shape)}"
                    )
                n = self.mesh.shape[self.data_axis]
            elif cfg.diloco_workers >= 1:
                n = cfg.diloco_workers
            else:
                raise ValueError(
                    "dp_mode='diloco' needs a mesh (the gang is the "
                    f"{self.data_axis!r} axis) or diloco_workers >= 1 "
                    "(the vmapped single-device gang emulation)"
                )
            if cfg.batch_size % n:
                raise ValueError(
                    f"dp_mode='diloco' shards the batch over {n} "
                    f"workers: batch_size {cfg.batch_size} must divide"
                )
            return "diloco"
        if self.mesh is None:
            return "single"
        if not cfg.sync:
            if cfg.dp_mode != "replicated":
                # Fail loudly rather than silently train full replicated
                # per-chip copies under a config that asked for a sharded
                # layout: the async copies are per-chip by construction.
                raise ValueError(
                    f"dp_mode={cfg.dp_mode!r} does not compose with "
                    "sync=False: the async copies are per-chip by "
                    "construction; pick one"
                )
            if cfg.batch_size % self.mesh.shape[self.data_axis]:
                raise ValueError(
                    f"async mode shards the batch over {self.data_axis!r}: "
                    f"batch_size {cfg.batch_size} must be divisible by the "
                    f"axis size {self.mesh.shape[self.data_axis]}"
                )
            return "async"
        if cfg.dp_mode == "tp":
            if self.tp_axis not in self.mesh.shape:
                raise ValueError(
                    f"dp_mode='tp' needs a {self.tp_axis!r} mesh axis: "
                    f"{dict(self.mesh.shape)}"
                )
            if self.model.moe_experts is not None:
                raise ValueError(
                    "dp_mode='tp' is not defined for MoE blocks; use "
                    "dp_mode='ep' (expert parallelism)"
                )
            return "tp"
        if cfg.dp_mode == "ep":
            if self.model.moe_experts is None:
                raise ValueError(
                    "dp_mode='ep' requires a MoE model (moe_experts=E)"
                )
            if self.expert_axis not in self.mesh.shape:
                raise ValueError(
                    f"dp_mode='ep' needs a {self.expert_axis!r} mesh axis: "
                    f"{dict(self.mesh.shape)}"
                )
            shards = self.mesh.shape.get(self.expert_axis, 1) * (
                self.mesh.shape.get(self.data_axis, 1)
                if self._dp_axis() is not None
                else 1
            )
            if cfg.batch_size % shards:
                raise ValueError(
                    f"dp_mode='ep' shards the batch {shards} ways: "
                    f"batch_size {cfg.batch_size} must divide"
                )
            return "ep"
        if cfg.dp_mode == "pp":
            if self.stage_axis not in self.mesh.shape:
                raise ValueError(
                    f"dp_mode='pp' needs a {self.stage_axis!r} mesh axis: "
                    f"{dict(self.mesh.shape)}"
                )
            m = self.pp_microbatches
            if cfg.batch_size % m:
                raise ValueError(
                    f"dp_mode='pp' splits the batch into {m} microbatches: "
                    f"batch_size {cfg.batch_size} must be divisible"
                )
            d = self.mesh.shape.get(self.data_axis, 1)
            if self._dp_axis() is not None and (cfg.batch_size // m) % d:
                raise ValueError(
                    f"dp×pp shards each {cfg.batch_size // m}-row "
                    f"microbatch over the {d}-way {self.data_axis!r} axis: "
                    "sizes must divide"
                )
            return "pp"
        if cfg.dp_mode == "sp":
            if self.seq_axis not in self.mesh.shape:
                raise ValueError(
                    f"dp_mode='sp' needs a {self.seq_axis!r} mesh axis: "
                    f"{dict(self.mesh.shape)}"
                )
            if self.model.moe_experts is not None:
                raise ValueError(
                    "dp_mode='sp' is not defined for MoE blocks; use "
                    "dp_mode='ep' (expert parallelism)"
                )
            s = self.mesh.shape[self.seq_axis]
            seq_len = self.datasets.train.tokens.shape[1]
            if seq_len % s:
                raise ValueError(
                    f"dp_mode='sp' shards the {seq_len}-token sequence "
                    f"over the {s}-way {self.seq_axis!r} axis: must divide"
                )
            d = self.mesh.shape.get(self.data_axis, 1)
            if self._dp_axis() is not None and cfg.batch_size % d:
                raise ValueError(
                    f"dp×sp shards the batch over the {d}-way "
                    f"{self.data_axis!r} axis: batch_size {cfg.batch_size} "
                    "must divide"
                )
            return "sp"
        if cfg.dp_mode == "zero":
            return "zero"
        return "dp"

    def _dp_axis(self) -> str | None:
        """The data axis to compose on top of tp/ep/pp — present on the
        mesh or None (pure tp / ep / pp meshes are legal)."""
        return self.data_axis if self.data_axis in self.mesh.shape else None

    def _init_state(self, params) -> TrainState:
        if self.mode == "pp":
            # Parts first (their validations), then restage the params so
            # the optimizer slots are born in the staged layout.
            from distributed_tensorflow_tpu.models.gpt import (
                make_lm_pp_parts,
                pipeline_stage_params,
            )

            specs, opt_specs, self._pp_loss = make_lm_pp_parts(
                self.model,
                self.optimizer,
                self.mesh,
                axis=self.stage_axis,
                num_microbatches=self.pp_microbatches,
                data_axis=self._dp_axis(),
            )
            params = pipeline_stage_params(
                self.model, params, self.mesh.shape[self.stage_axis]
            )
            return self._sharded_init(params, specs, opt_specs=opt_specs)
        opt_state = self.optimizer.init(params)
        if self.mode == "zero":
            from distributed_tensorflow_tpu.parallel import fsdp_specs

            pshape = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
            )
            pspecs = fsdp_specs(pshape, self.mesh, axis=self.data_axis)
            return self._sharded_init(params, pspecs, opt_state=opt_state)
        if self.mode == "tp":
            return self._sharded_init(
                params,
                self.model.partition_specs(self.tp_axis),
                opt_state=opt_state,
            )
        if self.mode == "ep":
            from distributed_tensorflow_tpu.models.gpt import make_lm_ep_parts

            specs, opt_specs, self._mapped_update = make_lm_ep_parts(
                self.model,
                self.optimizer,
                self.mesh,
                self.expert_axis,
                data_axis=self._dp_axis(),
                ragged=self._ragged,
            )
            return self._sharded_init(
                params, specs, opt_specs=opt_specs, opt_state=opt_state
            )
        if self.mode == "sp":
            from distributed_tensorflow_tpu.models.gpt import make_lm_sp_parts

            self._mapped_update = make_lm_sp_parts(
                self.model,
                self.optimizer,
                self.mesh,
                self.seq_axis,
                data_axis=self._dp_axis(),
                attention=self.sp_attention,
                ragged=self._ragged,
            )
            # Params stay replicated (sp shards activations, not weights):
            # the plain TrainState below is already the right layout.
        if self.mode == "diloco":
            from distributed_tensorflow_tpu.train.local_sgd import (
                make_lm_diloco_parts,
                make_lm_diloco_vmapped,
            )

            kw = dict(
                # Mailbox gang: the in-graph exchange must never fire —
                # the boundary is a host decision point (an unreachable
                # period, the async avg_every=0 trick); the engine still
                # allocates the EF residual (it checkpoints with the
                # state), which the host round updates.
                sync_every=(
                    (1 << 30)
                    if self.delta_exchange is not None
                    else self.config.sync_every
                ),
                outer_lr=self.config.outer_lr,
                outer_momentum=self.config.outer_momentum,
                ragged=self._ragged,
                delta_dtype=self.config.delta_dtype,
                overlap=self.config.delta_overlap,
            )
            if self.mesh is not None:
                init_state, self._diloco_mapped = make_lm_diloco_parts(
                    self.model,
                    self.optimizer,
                    self.mesh,
                    axis=self.data_axis,
                    **kw,
                )
            else:
                init_state, self._diloco_mapped = make_lm_diloco_vmapped(
                    self.model,
                    self.optimizer,
                    self.config.diloco_workers,
                    **kw,
                )
            stacked_p, dstate, count = init_state(params, opt_state)
            return TrainState(stacked_p, dstate, count)
        if self.mode == "async":
            from distributed_tensorflow_tpu.models.gpt import (
                make_lm_async_parts,
            )

            init_state, self._async_mapped = make_lm_async_parts(
                self.model,
                self.optimizer,
                self.mesh,
                axis=self.data_axis,
                # async_avg_every=0 means "never exchange" (classifier
                # convention) — key the cond on an unreachable period.
                avg_every=self.config.async_avg_every or (1 << 30),
                update_scale=self.async_update_scale,
                ragged=self._ragged,
            )
            stacked_p, stacked_o, count = init_state(params, opt_state)
            return TrainState(stacked_p, stacked_o, count)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    def _sharded_init(
        self, params, pspecs, *, opt_specs=None, opt_state=None
    ) -> TrainState:
        """Shared state construction for every GSPMD-sharded-layout mode
        (zero / tp / ep / pp): record the param + optimizer-slot shardings
        and place both pytrees under them."""
        from distributed_tensorflow_tpu.parallel import as_shardings, slot_specs

        if opt_specs is None:
            pshape = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
            )
            opt_specs = slot_specs(self.optimizer, pshape, pspecs)
        self._param_shardings = as_shardings(self.mesh, pspecs)
        self._opt_shardings = as_shardings(self.mesh, opt_specs)
        if opt_state is None:
            opt_state = self.optimizer.init(params)
        return TrainState(
            jax.device_put(params, self._param_shardings),
            jax.device_put(opt_state, self._opt_shardings),
            jnp.zeros((), jnp.int32),
        )

    def _place_state(self, state: TrainState) -> TrainState:
        """Re-place a state pytree into the mode's device layout. Needed
        after Supervisor restore: orbax hands back arrays committed to the
        default device, and a committed single-device leaf conflicts with
        the mesh-placed staging arrays under jit ("incompatible devices").
        Idempotent for already-placed states."""
        if self.mesh is None:
            return state
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        if self.mode in ("zero", "tp", "ep", "pp"):
            return TrainState(
                jax.device_put(state.params, self._param_shardings),
                jax.device_put(state.opt_state, self._opt_shardings),
                jax.device_put(state.step, repl),
            )
        if self.mode == "async":
            stacked = NamedSharding(self.mesh, P(self.data_axis))
            return TrainState(
                jax.device_put(state.params, stacked),
                jax.device_put(state.opt_state, stacked),
                jax.device_put(state.step, repl),
            )
        if self.mode == "diloco":
            # Worker copies + inner opt slots stacked over the gang; the
            # outer state (θ_start, momentum, and the round-17 EF
            # residual / in-flight delta when present) replicated — each
            # is ONE gang-level quantity, not per-worker.
            stacked = NamedSharding(self.mesh, P(self.data_axis))
            d = state.opt_state
            put_repl = lambda t: (  # noqa: E731 — None = lever off
                None if t is None else jax.device_put(t, repl)
            )
            return TrainState(
                jax.device_put(state.params, stacked),
                d._replace(
                    inner=jax.device_put(d.inner, stacked),
                    theta=jax.device_put(d.theta, repl),
                    momentum=jax.device_put(d.momentum, repl),
                    residual=put_repl(d.residual),
                    inflight=put_repl(d.inflight),
                ),
                jax.device_put(state.step, repl),
            )
        return TrainState(
            jax.device_put(state.params, repl),
            jax.device_put(state.opt_state, repl),
            jax.device_put(state.step, repl),
        )

    def _eval_params(self, params):
        """Parameters the held-out metric is computed at: async evaluates
        the mean of the per-chip copies (strategy.py convention), pp
        merges the staged layer groups back to the [num_layers, ...]
        stack (pure reshape — the dense forward then reads the same
        weights the pipeline trains), every other mode the parameters
        themselves. Works traced (the compiled run folds in-graph) and
        concrete alike. DiLoCo evaluates where async does — at the mean
        of the worker copies (== θ_start exactly on round boundaries,
        and the natural mid-round point between them)."""
        if self.mode in ("async", "diloco"):
            return jax.tree.map(lambda x: jnp.mean(x, axis=0), params)
        if self.mode == "pp":
            return params._replace(
                blocks=jax.tree.map(
                    lambda a: a.reshape((-1,) + a.shape[2:]), params.blocks
                )
            )
        return params

    # -- cross-topology checkpoint restore (round 5) -----------------------
    #
    # Every mode's state is a re-layout of ONE canonical form — the dense
    # single-device (params, opt_state, step): {single, dp, zero, tp, ep,
    # sp} share its shapes outright (only GSPMD placement differs), pp
    # stages the block stack ([L] → [S, L/S]), async stacks N per-replica
    # copies. A checkpoint therefore restores into ANY mode: restore in
    # the source layout's shapes, canonicalize (pp unstages; async merges
    # at the mean — the same parameters async evaluates at), then re-stage
    # into the target layout. Same-layout resume keeps the old bitwise
    # path (async replicas keep their individual copies). The reference's
    # Supervisor could only re-attach to the same topology (reference
    # tfdist_between.py:78,83) — this is the elasticity upgrade SURVEY §5
    # flagged as the deliberate next axis.

    # Modes whose state shapes ARE the canonical shapes.
    _DENSE_LAYOUTS = frozenset({"single", "dp", "zero", "tp", "ep", "sp"})

    def _layout_meta(self) -> dict:
        """Topology descriptor saved alongside each checkpoint — shape
        keys (mode/stages/replicas) plus the round-8 restore policy: the
        world size (device count) and the GLOBAL batch, which a resized
        gang's restore must find unchanged (the LM ``batch_size`` is
        already global — docs/resilience.md, batch policy)."""
        meta: dict = {"mode": self.mode}
        if self.mode == "pp":
            meta["stages"] = int(self.mesh.shape[self.stage_axis])
        if self.mode == "async":
            meta["replicas"] = int(self.mesh.shape[self.data_axis])
        if self.mode == "diloco":
            # replicas = the LOCAL stacked width (what the saved arrays'
            # leading axis is): the mailbox gang stacks ONE member per
            # process regardless of how many peers share the exchange.
            meta["replicas"] = (
                1
                if self.delta_exchange is not None
                else int(self._gang_size())
            )
            # POLICY key (like world/global_batch): the outer-round
            # length is a schedule knob, not a shape — layout_shape
            # ignores it, so resuming under a different H keeps the
            # bitwise same-layout path.
            meta["sync_every"] = int(self.config.sync_every)
            # Round-17 lever keys, present only when ON (lever-off metas
            # stay byte-identical to round 14). These ARE shape keys
            # (supervisor.LAYOUT_SHAPE_KEYS): the EF residual and the
            # in-flight delta are extra DiLoCoState nodes, so flipping a
            # lever between save and resume must route through the
            # cross-topology path, never the bitwise one.
            if self.config.delta_dtype:
                meta["delta_dtype"] = self.config.delta_dtype
            if self.config.delta_overlap:
                meta["overlap"] = True
        meta["world"] = int(
            1 if self.mesh is None else self.mesh.size
        )
        meta["global_batch"] = int(self.config.batch_size)
        return meta

    def _gang_size(self) -> int:
        """Workers in the data-parallel gang (1 when there is none):
        the data-axis size, or the emulated diloco gang width."""
        if self.mesh is not None and self.data_axis in self.mesh.shape:
            return int(self.mesh.shape[self.data_axis])
        if self.mode == "diloco":
            if self.delta_exchange is not None:
                return int(self.delta_exchange.world)
            return int(self.config.diloco_workers)
        return 1

    def _layout_compatible(self, src: dict) -> bool:
        """True when the saved state's SHAPES match this trainer's (the
        bitwise same-layout resume path applies). Compared on the shape
        keys only (supervisor.layout_shape): the round-8 policy keys
        (world/global_batch) ride the same sidecar but a world-size
        change alone is a pure re-shard for every dense-family mode."""
        from distributed_tensorflow_tpu.train.supervisor import layout_shape

        m = src.get("mode")
        if self.mode in self._DENSE_LAYOUTS:
            return m in self._DENSE_LAYOUTS
        return m == self.mode and layout_shape(src) == layout_shape(
            self._layout_meta()
        )

    def _map_params_like(self, fn, tree_):
        """Apply ``fn`` to every GPTLMParams node in a pytree — the
        optimizer state mirrors the parameter structure (adam's mu/nu ARE
        GPTLMParams), so one traversal re-layouts params and slots alike;
        non-params leaves (e.g. adam's count) pass through."""
        from distributed_tensorflow_tpu.models.gpt import GPTLMParams

        return jax.tree.map(
            lambda node: fn(node) if isinstance(node, GPTLMParams) else node,
            tree_,
            is_leaf=lambda x: isinstance(x, GPTLMParams),
        )

    def _abstract_state_for(self, src: dict) -> TrainState:
        """ShapeDtypeStructs of a checkpoint written under layout ``src``
        (this model + optimizer; cross-OPTIMIZER restore is out of scope —
        orbax fails loudly on a structure mismatch). Leaves are pinned to
        the default LOCAL device: eval_shape structs carry sharding=None,
        which some orbax vintages cannot normalize (the serve.py
        canonical_lm_params gotcha, round 9) — and it must be
        ``local_devices`` because every rank of a multi-process gang
        restores (``jax.devices()[0]`` is non-addressable on rank > 0)."""
        dev = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=dev),
            self._abstract_state_shapes(src),
        )

    def _abstract_state_shapes(self, src: dict) -> TrainState:
        params = jax.eval_shape(lambda: self.model.init(seed=0))
        if src["mode"] == "pp":
            from distributed_tensorflow_tpu.models.gpt import (
                pipeline_stage_params,
            )

            params = jax.eval_shape(
                lambda p: pipeline_stage_params(
                    self.model, p, src["stages"]
                ),
                params,
            )
        opt = jax.eval_shape(self.optimizer.init, params)
        step = jax.ShapeDtypeStruct((), jnp.int32)
        if src["mode"] in ("async", "diloco"):
            n = src["replicas"]
            stack = lambda t: jax.tree.map(  # noqa: E731
                lambda s: jax.ShapeDtypeStruct((n,) + s.shape, s.dtype), t
            )
            if src["mode"] == "diloco":
                from distributed_tensorflow_tpu.train.local_sgd import (
                    DiLoCoState,
                )

                # Outer anchor + momentum carry DENSE parameter shapes
                # regardless of the gang size (world-invariant) — and so
                # do the round-17 EF residual / in-flight delta, present
                # exactly when the saving config had the lever on (the
                # sidecar's shape keys say so).
                return TrainState(
                    stack(params),
                    DiLoCoState(
                        stack(opt),
                        params,
                        params,
                        params if src.get("delta_dtype") else None,
                        {"delta": params, "landing": params}
                        if src.get("overlap")
                        else None,
                    ),
                    step,
                )
            return TrainState(stack(params), stack(opt), step)
        return TrainState(params, opt, step)

    def _state_to_canonical(self, state: TrainState, src: dict) -> TrainState:
        """Source-layout state → dense single-device layout."""
        mode = src["mode"]
        if mode == "async":
            # Merge the replicas at the mean — exactly the parameters the
            # async mode itself evaluates at (_eval_params). Integer
            # leaves (adam count) take replica 0's value outright
            # (strategy.merge_replica_leaf): the float mean is exact only
            # below 2^24, past which mean-then-cast silently corrupts the
            # count the copies share (ADVICE round 5).
            from distributed_tensorflow_tpu.parallel.strategy import (
                merge_replica_leaf,
            )

            merge = lambda t: jax.tree.map(merge_replica_leaf, t)  # noqa: E731
            return TrainState(
                merge(state.params), merge(state.opt_state), state.step
            )
        if mode == "diloco":
            # Same merge-at-the-mean as async for the worker copies and
            # inner slots (merge_replica_leaf keeps integer leaves exact);
            # the OUTER state (θ_start, momentum) has no canonical slot —
            # the diloco→diloco resize path carries it verbatim instead
            # (__init__), every other destination starts a fresh outer
            # round from the merged parameters.
            from distributed_tensorflow_tpu.parallel.strategy import (
                merge_replica_leaf,
            )

            merge = lambda t: jax.tree.map(merge_replica_leaf, t)  # noqa: E731
            return TrainState(
                merge(state.params),
                merge(state.opt_state.inner),
                state.step,
            )
        if mode == "pp":
            unstage = lambda p: p._replace(  # noqa: E731
                blocks=jax.tree.map(
                    lambda a: a.reshape((-1,) + a.shape[2:]), p.blocks
                )
            )
            return TrainState(
                self._map_params_like(unstage, state.params),
                self._map_params_like(unstage, state.opt_state),
                state.step,
            )
        return state

    def _state_from_canonical(self, c: TrainState) -> TrainState:
        """Dense single-device layout → this trainer's layout (placement
        itself happens in _place_state)."""
        if self.mode == "pp":
            from distributed_tensorflow_tpu.models.gpt import (
                pipeline_stage_params,
            )

            stages = int(self.mesh.shape[self.stage_axis])
            stage = lambda p: pipeline_stage_params(  # noqa: E731
                self.model, p, stages
            )
            return TrainState(
                self._map_params_like(stage, c.params),
                self._map_params_like(stage, c.opt_state),
                c.step,
            )
        if self.mode == "async":
            n = int(self.mesh.shape[self.data_axis])
            bcast = lambda t: jax.tree.map(  # noqa: E731
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t
            )
            return TrainState(bcast(c.params), bcast(c.opt_state), c.step)
        if self.mode == "diloco":
            from distributed_tensorflow_tpu.train.local_sgd import (
                DiLoCoState,
            )

            # Mailbox gangs stack ONE member per process regardless of
            # the gang's world size.
            n = 1 if self.delta_exchange is not None else self._gang_size()
            bcast = lambda t: jax.tree.map(  # noqa: E731
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t
            )
            zeros = lambda: jax.tree.map(  # noqa: E731
                jnp.zeros_like, c.params
            )
            # Fresh outer round from the canonical point: anchor at the
            # restored params, zero momentum — and zero EF residual /
            # in-flight delta when this trainer's levers are on (a dense
            # source has none to carry; the diloco→diloco resize
            # overwrites all of them with the saved outer state —
            # __init__).
            return TrainState(
                bcast(c.params),
                DiLoCoState(
                    bcast(c.opt_state),
                    c.params,
                    zeros(),
                    zeros() if self.config.delta_dtype else None,
                    # Nothing in flight; every copy lands on the
                    # restored point (a copy — aliasing theta would
                    # donate the same buffer twice under the scan).
                    {"delta": zeros(), "landing": jax.tree.map(jnp.copy, c.params)}
                    if self.config.delta_overlap
                    else None,
                ),
                c.step,
            )
        return c

    # -- compiled pieces ---------------------------------------------------

    @property
    def global_step(self) -> int:
        return int(self.state.step)

    def _replicated(self, a):
        """Host array → device, replicated over the mesh when present."""
        if self.mesh is None:
            return jnp.asarray(a)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(np.asarray(a), NamedSharding(self.mesh, P()))

    def _stage(self, name: str, arr):
        """Device-resident staging cache (same contract as
        Trainer._stage_cached): token arrays placed once, reused across
        epochs/evals — per-epoch upload is only the int32 index block."""
        hit = self._stage_cache.get(name)
        if hit is None or hit[0] is not arr:
            self._stage_cache[name] = hit = (arr, self._replicated(arr))
        return hit[1]

    def _train_lens(self):
        """Staged train lengths — real when ragged, else a once-staged zero
        placeholder (the compiled bodies statically ignore it; staging
        avoids a per-epoch upload)."""
        train = self.datasets.train
        if self._ragged:
            return self._stage("train_lengths", train.lengths)
        if not hasattr(self, "_zero_lens"):
            self._zero_lens = np.zeros((train.num_examples,), np.int32)
        return self._stage("zero_lengths", self._zero_lens)

    def _shard_batch(self, toks):
        if self.mesh is None:
            return toks
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Pure-tp / pure-pp meshes have no data axis: the batch stays
        # replicated (the sharded dimension is the model, not the batch).
        spec = P(self.data_axis) if self._dp_axis() is not None else P()
        return jax.lax.with_sharding_constraint(
            toks, NamedSharding(self.mesh, spec)
        )

    def _loss(self, params, toks, lens):
        if lens is None:
            return self.model.loss(params, toks)
        return self.model.loss(params, toks, lens)

    def _build_eager_step(self):
        """One per-batch jitted step, uniform across modes:
        ``step(params, opt_state, count, toks, lens) -> (params, opt_state,
        loss)`` (``count`` drives the async exchange cadence; the sync
        modes ignore it)."""
        if self.mode in ("async", "diloco"):
            mapped = (
                self._async_mapped
                if self.mode == "async"
                else self._diloco_mapped
            )
            ragged = self._ragged

            @jax.jit
            def astep(params, opt_state, count, toks, lens):
                return mapped(
                    params, opt_state, toks, lens if ragged else None, count
                )

            return astep
        if self.mode in ("ep", "sp"):
            mapped = self._mapped_update
            ragged = self._ragged

            @jax.jit
            def estep(params, opt_state, count, toks, lens):
                return mapped(
                    params, opt_state, toks, lens if ragged else None
                )

            return estep
        if self.mode in ("zero", "tp", "pp"):
            from distributed_tensorflow_tpu.parallel import pinned_update

            opt = self.optimizer
            loss_fn = (
                self._pp_loss if self.mode == "pp" else self.model.loss
            )
            shardings = self._param_shardings
            opt_shardings = self._opt_shardings
            shard = self._shard_batch

            @jax.jit
            def zstep(params, opt_state, count, toks, lens):
                toks = shard(toks)
                loss, grads = jax.value_and_grad(loss_fn)(
                    params, toks, lens
                )
                # Owner layout (zero: the batch-sum over 'data' lowers to
                # a reduce-scatter; tp: Megatron column/row shards; pp:
                # stage-owned layer groups) — the update stays local to
                # each chip's slice.
                params, opt_state = pinned_update(
                    opt, params, opt_state, grads, shardings, opt_shardings
                )
                return params, opt_state, loss

            return zstep
        if self._ragged:
            # make_lm_train_step has no lengths slot; build the equivalent
            # jitted step over (tokens, lengths) with the masked loss.
            model, opt = self.model, self.optimizer

            @jax.jit
            def step(params, opt_state, count, toks, lens):
                loss, grads = jax.value_and_grad(model.loss)(
                    params, toks, lens
                )
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

            return step
        plain = make_lm_train_step(self.model, self.optimizer, mesh=self.mesh)

        def step(params, opt_state, count, toks, lens):
            return plain(params, opt_state, toks)

        return step

    def _make_step_body(self, toks_all, lens_all):
        """The ONE compiled step body per mode, shared by the scanned-epoch
        and whole-run paths (a divergence here would silently break their
        proven equality): gather the batch by index from the staged
        arrays, shard it over the mesh, masked loss when ragged; the async
        body is the shard-mapped local-SGD update keyed on the carried
        step count, the zero body pins grads/params/slots to the FSDP
        layout so the carry stays sharded across the whole scan."""
        model, opt = self.model, self.optimizer
        ragged = self._ragged
        shard = self._shard_batch
        if self.mode in ("async", "diloco"):
            mapped = (
                self._async_mapped
                if self.mode == "async"
                else self._diloco_mapped
            )

            def abody(carry, idx):
                params, opt_state, step = carry
                toks = toks_all[idx]
                lens = lens_all[idx] if ragged else None
                params, opt_state, loss = mapped(
                    params, opt_state, toks, lens, step
                )
                return (params, opt_state, step + 1), loss

            return abody
        if self.mode in ("ep", "sp"):
            mapped = self._mapped_update

            def ebody(carry, idx):
                params, opt_state, step = carry
                toks = toks_all[idx]
                lens = lens_all[idx] if ragged else None
                params, opt_state, loss = mapped(
                    params, opt_state, toks, lens
                )
                return (params, opt_state, step + 1), loss

            return ebody
        pinned = self.mode in ("zero", "tp", "pp")
        loss_fn = self._pp_loss if self.mode == "pp" else model.loss
        if pinned:
            from distributed_tensorflow_tpu.parallel import pinned_update

        def body(carry, idx):
            params, opt_state, step = carry
            toks = shard(toks_all[idx])
            lens = lens_all[idx] if ragged else None
            loss, grads = jax.value_and_grad(loss_fn)(params, toks, lens)
            if pinned:
                params, opt_state = pinned_update(
                    opt, params, opt_state, grads,
                    self._param_shardings, self._opt_shardings,
                )
            else:
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
            return (params, opt_state, step + 1), loss

        return body

    def _build_scanned_fn(self):
        def epoch(state, toks_all, lens_all, idxs):
            body = self._make_step_body(toks_all, lens_all)
            carry = (state.params, state.opt_state, state.step)
            (p, o, s), losses = jax.lax.scan(body, carry, idxs)
            return TrainState(p, o, s), losses

        return jax.jit(epoch, donate_argnums=0)

    def _ce_count(self, params, toks, lens):
        """(CE · target-count, target-count) for one token block — the ONE
        eval arithmetic shared by the host-side :meth:`evaluate` chunks and
        the compiled run's in-graph eval (a divergence here would silently
        break their proven equality, same rationale as
        :meth:`_make_step_body`); masked when ragged."""
        l = toks.shape[1]
        if self._ragged:
            ce = self.model.loss(params, toks, lens)
            count = jnp.sum(jnp.maximum(lens - 1, 0))
        else:
            ce = self.model.loss(params, toks)
            count = jnp.asarray(toks.shape[0] * (l - 1), jnp.int32)
        return ce * count, count

    def _in_graph_perplexity(self, params, val_toks, val_lens):
        """Per-epoch eval inside the compiled run: chunked over
        ``eval_batch``-row blocks (trimmed to a chunk multiple), exact
        CE·count aggregation via :meth:`_ce_count`."""
        ragged = self._ragged
        n, l = val_toks.shape
        b = min(self.eval_batch, n)
        k = n // b
        vt = val_toks[: k * b].reshape(k, b, l)
        vl = val_lens[: k * b].reshape(k, b) if ragged else None

        def chunk(args):
            toks, lens = args
            return self._ce_count(params, toks, lens if ragged else None)

        sums, counts = jax.lax.map(
            chunk, (vt, vl if ragged else jnp.zeros((k, b), jnp.int32))
        )
        return jnp.exp(jnp.sum(sums) / jnp.maximum(jnp.sum(counts), 1))

    def _build_compiled_run_fn(self):
        """The LM analog of ``train/compiled_run.py``: EVERY epoch's steps
        AND its held-out perplexity eval compiled into ONE dispatch — an
        outer scan over epochs, an inner scan over that epoch's gathered
        batches (the SAME step body as the scanned path), and an in-graph
        chunked eval over the staged validation tokens. Identical update
        math to the scanned path: the [epochs, steps, batch] index block is
        drawn from the dataset's own ``next_indices`` stream (proven
        bitwise in test_lm_trainer.py)."""

        def run(state, toks_all, lens_all, idxs, val_toks, val_lens):
            step_body = self._make_step_body(toks_all, lens_all)

            def epoch_body(carry, epoch_idxs):
                carry, losses = jax.lax.scan(step_body, carry, epoch_idxs)
                ppl = self._in_graph_perplexity(
                    self._eval_params(carry[0]), val_toks, val_lens
                )
                return carry, (losses, ppl)

            carry = (state.params, state.opt_state, state.step)
            (p, o, s), (losses, ppls) = jax.lax.scan(
                epoch_body, carry, idxs
            )
            return TrainState(p, o, s), losses, ppls

        return jax.jit(run, donate_argnums=0)

    def run_compiled(
        self,
        epochs: int | None = None,
        *,
        epoch_offset: int = 0,
        finalize: bool = True,
    ) -> dict:
        """Trace-scoped entry for :meth:`_run_compiled` (the whole-run
        fast path — full contract on the implementation just below): one
        trace id per run, reusing run()'s when chunked dispatches arrive
        inside it."""
        from distributed_tensorflow_tpu.observability import tracing

        if self.delta_exchange is not None:
            raise ValueError(
                "run_compiled does not compose with delta_exchange: the "
                "mailbox round is a host decision point inside every "
                "epoch; use run()"
            )
        with tracing.trace(tracing.current_trace()):
            try:
                return self._run_compiled(
                    epochs, epoch_offset=epoch_offset, finalize=finalize
                )
            finally:
                if finalize and self.supervisor is not None:
                    self.supervisor.wait_pending()

    def _run_compiled(
        self,
        epochs: int | None = None,
        *,
        epoch_offset: int = 0,
        finalize: bool = True,
    ) -> dict:
        """Whole-run fast path: all epochs + per-epoch in-graph perplexity
        as ONE dispatch. Log lines (uniform AvgTime), summaries, and
        history match :meth:`run`; the in-graph perplexity covers the
        validation split trimmed to an ``eval_batch`` multiple (equal to
        :meth:`evaluate` whenever ``eval_batch`` divides the split; the
        final returned perplexity always comes from the exact full-split
        :meth:`evaluate`). Supervisor semantics differ BY DESIGN from
        run(): one checkpoint save after the dispatch and no mid-run
        heartbeat-reactive stop — a single compiled program cannot be
        interrupted at epoch boundaries; use run() when those matter, or
        ``config.epochs_per_dispatch`` for the middle tier (k epochs per
        dispatch with checkpoints + stop checks between dispatches —
        ``epoch_offset``/``finalize`` are its chunk plumbing)."""
        cfg = self.config
        epochs = cfg.epochs if epochs is None else epochs
        train = self.datasets.train
        val = self.datasets.validation
        steps = train.num_examples // cfg.batch_size
        logger = StepLogger(
            freq=cfg.log_frequency, print_fn=self.print_fn,
            journal=self.journal,
        )
        if epochs * steps == 0:
            # Nothing to dispatch (epochs=0, or dataset smaller than one
            # batch) — mirror run()'s no-op semantics instead of crashing
            # on an empty index stack.
            perplexity = self.evaluate("validation")  # all processes (global mesh)
            if self.is_chief:
                logger.log_final(cost=float("nan"))
            return {
                "perplexity": perplexity,
                "final_cost": float("nan"),
                "global_step": self.global_step,
            }

        # One jitted whole-run program, built once: it closes over nothing
        # shape-specific, so jax.jit's own shape-keyed cache handles varying
        # (epochs, steps) without re-tracing a rebuilt wrapper.
        if not hasattr(self, "_compiled_run_fn"):
            self._compiled_run_fn = self._build_compiled_run_fn()
        run_fn = self._compiled_run_fn
        toks = self._stage("train_tokens", train.tokens)
        lens = self._train_lens()
        if self._ragged:
            val_lens = self._stage("validation_lengths", val.lengths)
        else:
            val_lens = None
        val_toks = self._stage("validation_tokens", val.tokens)
        idxs = self._replicated(
            np.stack(
                [
                    self._epoch_indices(steps, cfg.batch_size)
                    for _ in range(epochs)
                ]
            )
        )
        step_before = self.global_step
        mark = self.spans.mark()
        t0 = time.time()
        self.state, costs, ppls = run_fn(
            self.state, toks, lens, idxs, val_toks, val_lens
        )
        # D2H fetch = execution barrier; dispatch_fetch also records the
        # honest dispatch span (CLAUDE.md timing trap).
        costs = self.spans.dispatch_fetch(
            "lm_compiled_run", costs, start=mark, epochs=int(epochs)
        )
        ppls = jax.device_get(ppls)
        elapsed = time.time() - t0
        avg_ms = elapsed * 1000 / max(epochs * steps, 1)
        self._observe_step_time(avg_ms)
        self.last_cost = float(costs[-1, -1])
        for epoch in range(epochs):
            for i in range(steps):
                if logger.is_due(i + 1, steps):
                    logger.log_step_line(
                        step=step_before + epoch * steps + i + 1,
                        epoch=epoch_offset + epoch,
                        batch=i,
                        batch_count=steps,
                        cost=float(costs[epoch, i]),
                        avg_ms=avg_ms,
                    )
            self._emit_comm_stats(
                epoch=epoch_offset + epoch,
                steps=steps,
                count_before=step_before + epoch * steps,
            )
            if self.is_chief:
                ppl = float(ppls[epoch])
                logger.log_epoch_metric("Test-Perplexity", ppl)
                step_now = step_before + (epoch + 1) * steps
                if self.summary_writer is not None:
                    for i in range(steps):
                        self.summary_writer.add_scalar(
                            "cost",
                            float(costs[epoch, i]),
                            step_before + epoch * steps + i + 1,
                        )
                    self.summary_writer.add_scalar("perplexity", ppl, step_now)
                self.history.append(
                    {
                        "epoch": epoch_offset + epoch + 1,
                        "perplexity": ppl,
                        "step": step_now,
                    }
                )
        if self.supervisor is not None:
            self.supervisor.report_progress(self.global_step)
            if cfg.max_rollbacks and costs.size and not np.isfinite(costs).all():
                # One compiled dispatch cannot roll back mid-program; the
                # guard's durability half still holds — never commit a
                # poisoned state over the last good checkpoint (the
                # per-epoch run() path does the full restore+retry).
                if self.is_chief:
                    lifecycle_event(
                        "rollback_compiled",
                        print_fn=self.print_fn,
                        journal=self.journal,
                    )
            else:
                self.supervisor.save(
                    self.state, self.global_step, layout=self._layout_meta()
                )
        if not finalize:
            return {
                "perplexity": float(ppls[-1]),
                "final_cost": self.last_cost,
                "global_step": self.global_step,
            }
        perplexity = self.evaluate("validation")  # all processes (global mesh)
        if self.is_chief:
            logger.log_final(cost=self.last_cost)
            if self.summary_writer is not None:
                self.summary_writer.flush()
            self.metrics.flush_to(self.journal, component="lm_trainer")
            self.journal.flush()
        return {
            "perplexity": perplexity,
            "final_cost": self.last_cost,
            "global_step": self.global_step,
        }

    def _run_chunked(self, epochs: int) -> dict:
        """k-epochs-per-dispatch middle tier (``config.epochs_per_dispatch``,
        mirror of Trainer._run_chunked): the compiled whole-run program
        dispatched a chunk at a time — per-epoch logs + in-graph perplexity
        from each chunk's fetched history, checkpoint per dispatch,
        ``should_stop`` honored at chunk boundaries."""
        import math

        from distributed_tensorflow_tpu.train.resilience import AnomalyGuard

        k = self.config.epochs_per_dispatch
        guard = AnomalyGuard.from_config(self.config)
        res = {
            "perplexity": float("nan"),
            "final_cost": float("nan"),
            "global_step": self.global_step,
        }
        done = 0
        while done < epochs:
            n = min(k, epochs - done)
            last = done + n >= epochs
            step_before = self.global_step
            res = self.run_compiled(n, epoch_offset=done, finalize=last)
            if (
                guard is not None
                and not math.isfinite(res["final_cost"])
                and res["global_step"] > step_before
            ):
                # Chunk went NaN mid-dispatch (its save was skipped): roll
                # back at this host boundary and retry — the retried chunk
                # draws the NEXT next_indices window, so the offending
                # data is skipped, not replayed (NaN-only; see
                # Trainer._run_chunked). Empty dispatches (nan
                # placeholder, no step advance) are not anomalies.
                self._anomaly_rollback(guard, "nan", done)
                continue
            done += n
            if self.supervisor is not None and self.supervisor.should_stop:
                if not last:
                    res["perplexity"] = self.evaluate("validation")
                    if self.is_chief:
                        StepLogger(
                            freq=self.config.log_frequency,
                            print_fn=self.print_fn,
                            journal=self.journal,
                        ).log_final(cost=res["final_cost"])
                        if self.summary_writer is not None:
                            self.summary_writer.flush()
                break
        return res

    def _build_eval_chunk(self):
        @jax.jit
        def chunk(params, toks, lens):
            return self._ce_count(params, toks, lens)

        return chunk

    def evaluate(self, split: str = "validation") -> float:
        """Held-out perplexity = exp(total next-token CE / total targets)."""
        if self._eval_chunk is None:
            self._eval_chunk = self._build_eval_chunk()
        params = self.state.params
        if self.mode in ("async", "diloco", "pp"):
            # Fold to the eval layout ONCE per evaluate call (not per
            # chunk): async takes the mean of the stacked copies, pp
            # merges the staged layer groups — the parameters the metric
            # is defined at.
            if not hasattr(self, "_fold_fn"):
                self._fold_fn = jax.jit(self._eval_params)
            params = self._fold_fn(params)
        ds = getattr(self.datasets, split)
        toks = self._stage(f"{split}_tokens", ds.tokens)
        lens = (
            self._stage(f"{split}_lengths", ds.lengths)
            if self._ragged
            else None
        )
        total, count = 0.0, 0
        b = min(self.eval_batch, ds.num_examples)
        # Full split coverage: the tail chunk runs at its own (smaller)
        # shape — one extra compile, zero dropped examples.
        for lo in range(0, ds.num_examples, b):
            hi = min(lo + b, ds.num_examples)
            t = jax.lax.slice_in_dim(toks, lo, hi)
            ln = jax.lax.slice_in_dim(lens, lo, hi) if self._ragged else None
            s, c = self._eval_chunk(params, t, ln)
            total += float(s)
            count += int(c)
        return float(np.exp(total / max(count, 1)))

    # -- the loop ----------------------------------------------------------

    def _epoch_indices(self, steps: int, batch: int) -> np.ndarray:
        """[steps, batch] int32 drawn from the dataset's OWN index stream,
        so the scanned epoch sees exactly the batches the eager loop would
        (including tail-carry across reshuffles)."""
        train = self.datasets.train
        return np.stack(
            [train.next_indices(batch) for _ in range(steps)]
        ).astype(np.int32)

    def run_epoch(self, epoch: int, logger: StepLogger) -> None:
        cfg = self.config
        train = self.datasets.train
        steps = train.num_examples // cfg.batch_size
        summaries: list[tuple[int, float]] = []
        step_before = self.global_step
        self._epoch_costs = None  # eager path: guard judges last_cost only
        if self._scan:
            if self._scanned_fn is None:
                self._scanned_fn = self._build_scanned_fn()
            toks = self._stage("train_tokens", train.tokens)
            lens = self._train_lens()
            idxs = self._replicated(self._epoch_indices(steps, cfg.batch_size))
            mark = self.spans.mark()
            t0 = time.time()
            self.state, costs = self._scanned_fn(self.state, toks, lens, idxs)
            # D2H fetch = execution barrier (+ the honest dispatch span).
            costs = self.spans.dispatch_fetch(
                "lm_epoch_scan", costs, start=mark, epoch=int(epoch)
            )
            avg_ms = (time.time() - t0) * 1000 / steps
            self._observe_step_time(avg_ms)
            self.last_cost = float(costs[-1])
            self._epoch_costs = costs  # anomaly guard sees every step's cost
            for i in range(steps):
                if logger.is_due(i + 1, steps):
                    logger.log_step_line(
                        step=step_before + i + 1,
                        epoch=epoch,
                        batch=i,
                        batch_count=steps,
                        cost=float(costs[i]),
                        avg_ms=avg_ms,
                    )
                if self.summary_writer is not None and self.is_chief:
                    summaries.append((step_before + i + 1, float(costs[i])))
        else:
            if self._eager_step is None:
                self._eager_step = self._build_eager_step()
            logger.reset_window()
            t_epoch = time.time()
            for i in range(steps):
                batch = train.next_batch(cfg.batch_size)
                toks, lens = batch if self._ragged else (batch, None)
                params, opt_state, cost = self._eager_step(
                    self.state.params,
                    self.state.opt_state,
                    self.state.step,
                    jnp.asarray(toks),
                    None if lens is None else jnp.asarray(lens),
                )
                self.state = TrainState(
                    params, opt_state, self.state.step + 1
                )
                if self.delta_exchange is not None:
                    # Host-side count: the device scalar would cost a
                    # blocking D2H fetch per inner step.
                    self._maybe_mailbox_round(step_before + i + 1)
                self.last_cost = cost
                if self.summary_writer is not None and self.is_chief:
                    summaries.append((step_before + i + 1, cost))
                if logger.is_due(i + 1, steps):
                    logger.maybe_log_step(
                        step=step_before + i + 1,
                        epoch=epoch,
                        batch=i,
                        batch_count=steps,
                        cost=float(cost),
                    )
            self.last_cost = float(self.last_cost)
            self._observe_step_time(
                (time.time() - t_epoch) * 1000 / max(steps, 1)
            )
        if self.summary_writer is not None and self.is_chief:
            for step, cost in summaries:
                self.summary_writer.add_scalar("cost", float(cost), step)
        self._emit_comm_stats(
            epoch=epoch, steps=steps, count_before=step_before
        )

    def _emit_comm_stats(
        self, *, epoch: int, steps: int, count_before: int
    ) -> None:
        """Per-epoch communication accounting (round 14) — MEASURED
        counters, not claims: how many gang-level sync rounds this
        epoch's steps fired and the bytes they all-reduced (one round
        moves one dense parameter set: dp's per-step gradient all-reduce
        and diloco's per-H-steps parameter mean carry the same payload,
        so the round ratio IS the traffic ratio). Journal ``comm_stats``
        events feed ``obs_report``'s comm/compute section; the counters
        land in the metrics registry. Modes whose traffic is not a
        param-sized all-reduce per round (zero/tp/ep/pp/sp collectives)
        are out of scope."""
        if self.mode not in ("dp", "diloco") or steps <= 0:
            return
        if self.mode == "diloco":
            from distributed_tensorflow_tpu.train.local_sgd import (
                sync_rounds_between,
            )

            h = self.config.sync_every
            rounds = sync_rounds_between(
                count_before, count_before + steps, h
            )
        else:
            h = 1
            rounds = steps
        if not hasattr(self, "_dense_param_nbytes"):
            from distributed_tensorflow_tpu.train.local_sgd import (
                delta_payload_nbytes,
                params_nbytes,
            )

            shapes = jax.eval_shape(lambda: self.model.init(seed=0))
            self._dense_param_nbytes = params_nbytes(shapes)
            # What ONE round actually puts on the wire (round 17): the
            # dense payload, or its per-tensor-quantized form under
            # delta_dtype. dp always moves dense gradients.
            self._delta_payload_nbytes = delta_payload_nbytes(
                shapes,
                self.config.delta_dtype if self.mode == "diloco" else None,
            )
        nbytes = rounds * self._dense_param_nbytes
        payload = rounds * self._delta_payload_nbytes
        self.journal.emit(
            "comm_stats",
            epoch=int(epoch),
            mode=self.mode,
            steps=int(steps),
            sync_every=int(h),
            sync_rounds=int(rounds),
            allreduce_bytes=int(nbytes),
            payload_bytes=int(payload),
            delta_dtype=(
                self.config.delta_dtype if self.mode == "diloco" else None
            ),
            overlap=bool(
                self.mode == "diloco" and self.config.delta_overlap
            ),
            workers=int(self._gang_size()),
        )
        self.metrics.counter("sync_rounds_total").inc(int(rounds))
        self.metrics.counter("allreduce_bytes_total").inc(int(nbytes))
        self.metrics.counter("payload_bytes_total").inc(int(payload))

    def _maybe_mailbox_round(self, count: int) -> None:
        """Host-side outer round of the stale-tolerant mailbox gang
        (round 17; ``local_sgd.DeltaExchange``), fired on the same
        cadence as the in-graph exchange (step ``t`` fires iff ``(t+1) %
        sync_every == 0`` — ``count`` is the HOST-side post-step counter:
        fetching ``int(self.state.step)`` here would block on a device
        scalar every inner step, ~100 ms of pure synchronization per
        step on the tunneled TPU). Post this member's (EF-compressed)
        pseudo-gradient, assemble the staleness-weighted mean from
        whatever peers have posted — NEVER waiting — and apply the outer
        update locally; ``outer_lr=None`` scales by the round's ACTUAL
        total contributor weight (the variable-gang form of the η=N
        convention — see ``DeltaExchange.weighted_delta``). A
        ``delta_exchange`` journal event records the contributors and
        their ages; the on-disk payload size is the measured wire
        cost."""
        h = self.config.sync_every
        if h < 1 or count % h:
            return
        from distributed_tensorflow_tpu.train.local_sgd import (
            DiLoCoState,
            outer_apply,
            resolve_outer_lr,
        )

        t0 = time.perf_counter()
        round_idx = count // h - 1  # rounds are 0-based
        d: DiLoCoState = self.state.opt_state
        p = jax.tree.map(lambda x: x[0], self.state.params)
        delta = jax.tree.map(lambda t, q: t - q, d.theta, p)
        leaves, treedef = jax.tree.flatten(delta)
        np_leaves = [np.asarray(jax.device_get(x)) for x in leaves]
        residual = d.residual
        if self.config.delta_dtype is not None:
            r_leaves = [
                np.asarray(jax.device_get(x))
                for x in jax.tree.leaves(residual)
            ]
            corr = [a + b for a, b in zip(np_leaves, r_leaves)]
            # post() returns the DEQUANTIZED wire values — the residual
            # must see what peers read, not what we meant to send.
            own = self.delta_exchange.post(round_idx, corr)
            residual = jax.tree.unflatten(
                treedef,
                [
                    jnp.asarray(a - b)
                    for a, b in zip(corr, own)
                ],
            )
        else:
            own = self.delta_exchange.post(round_idx, np_leaves)
        mean, total_weight, contributors = (
            self.delta_exchange.weighted_delta(round_idx, own)
        )
        mean_delta = jax.tree.unflatten(
            treedef, [jnp.asarray(x) for x in mean]
        )
        # outer_lr=None → the round's actual total contributor weight,
        # NOT the fixed world size: η=N compensates an exact 1/N mean of
        # N contributions; a member alone in the mailbox applies its own
        # delta exactly once (weighted_delta docstring).
        eta = (
            float(total_weight)
            if self.config.outer_lr is None
            else resolve_outer_lr(self.config.outer_lr, self._gang_size())
        )
        theta2, m2 = outer_apply(
            d.theta,
            mean_delta,
            d.momentum,
            outer_lr=eta,
            outer_momentum=self.config.outer_momentum,
        )
        new_p = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (1,) + x.shape), theta2
        )
        self.state = TrainState(
            new_p,
            d._replace(theta=theta2, momentum=m2, residual=residual),
            self.state.step,
        )
        stale = [c for c in contributors if c[1] > 0]
        self.journal.emit(
            "delta_exchange",
            round=int(round_idx),
            rank=int(self.delta_exchange.rank),
            world=int(self.delta_exchange.world),
            contributors=[
                [int(r), int(age), float(w)] for r, age, w in contributors
            ],
            total_weight=float(total_weight),
            outer_lr=float(eta),
            stale_contributions=len(stale),
            delta_dtype=self.config.delta_dtype,
            payload_nbytes=self.delta_exchange.payload_nbytes(round_idx),
            # Host cost of the whole boundary (post + gather + apply) —
            # the gang bench's outer-round wall share reads THIS: the
            # mailbox never waits on a peer, so this is the entire
            # non-overlapped cost of an outer round.
            wall_ms=round((time.perf_counter() - t0) * 1000, 3),
        )
        self.metrics.counter("mailbox_rounds_total").inc()
        if stale:
            self.metrics.counter("stale_contributions_total").inc(
                len(stale)
            )

    def _observe_step_time(self, avg_ms: float) -> None:
        """Per-epoch average step time into the metrics registry (mirror
        of Trainer._observe_step_time)."""
        from distributed_tensorflow_tpu.observability.metrics import (
            TIME_MS_EDGES,
        )

        self.metrics.histogram("step_time_ms", edges=TIME_MS_EDGES).observe(
            float(avg_ms)
        )

    def _anomaly_rollback(self, guard, kind: str, epoch: int) -> None:
        """LM analog of Trainer._anomaly_rollback: restore the newest
        valid checkpoint (re-placed into this mode's device layout), keep
        the host index stream where it is — the offending epoch's
        ``next_indices`` draws are consumed, never replayed, so the retry
        trains on the next data window (the PaLM spike protocol). With no
        checkpoint yet the target is the deterministic seed re-init.
        Raises AnomalyError once ``max_rollbacks`` is spent."""
        from distributed_tensorflow_tpu.train.resilience import AnomalyError

        detected_step = self.global_step
        if self.supervisor is None or guard.exhausted:
            raise AnomalyError(
                f"anomalous cost (kind={kind}) at epoch {epoch} step "
                f"{detected_step} with no rollback budget left "
                f"({guard.rollbacks}/{guard.max_rollbacks} used"
                + ("" if self.supervisor else "; no supervisor") + ")"
            )
        guard.rollbacks += 1
        self.metrics.counter("rollbacks_total").inc()
        fresh = self._init_state(self.model.init(seed=self.config.seed))
        restored, restored_step = self.supervisor.prepare_or_restore(fresh)
        self.state = self._place_state(restored)
        self.last_cost = None
        if self.is_chief:
            # One lifecycle_event fans out to stdout + journal + tfevents.
            lifecycle_event(
                "rollback",
                print_fn=self.print_fn,
                journal=self.journal,
                writer=self.summary_writer,
                scalar=("rollback", float(restored_step), detected_step),
                anomaly=kind,
                epoch=epoch,
                detected_step=detected_step,
                restored_step=restored_step,
                rollback=guard.rollbacks,
                max_rollbacks=guard.max_rollbacks,
            )

    def run(self, epochs: int | None = None) -> dict:
        """Public entry: the whole run under the preemption contract —
        SIGTERM/SIGINT requests a stop, the loop exits at the next epoch
        (or dispatch-chunk) boundary with a final save, and the process
        can exit 0 (train/resilience.py)."""
        from distributed_tensorflow_tpu.observability import tracing
        from distributed_tensorflow_tpu.train.resilience import preemption_guard

        # Ambient trace (round 12): one id across every journal event of
        # this run — see Trainer.run. Reuses an enclosing trace.
        from distributed_tensorflow_tpu.train.resilience import arm_stall_dump

        arm_stall_dump()  # $DTF_STALL_DUMP (elastic launcher) or no-op
        with tracing.trace(tracing.current_trace()), preemption_guard(
            self.supervisor,
            enabled=self.config.handle_preemption,
            print_fn=self.print_fn,
            journal=self.journal,
        ):
            try:
                return self._run(epochs)
            finally:
                # Async-checkpoint drain (round 22): run() returns only
                # once every submitted save is durable on disk.
                if self.supervisor is not None:
                    self.supervisor.wait_pending()

    def _run(self, epochs: int | None = None) -> dict:
        cfg = self.config
        epochs = cfg.epochs if epochs is None else epochs
        if cfg.epochs_per_dispatch:
            return self._run_chunked(epochs)
        logger = StepLogger(
            freq=cfg.log_frequency, print_fn=self.print_fn,
            journal=self.journal,
        )
        from distributed_tensorflow_tpu.train.resilience import AnomalyGuard

        guard = AnomalyGuard.from_config(cfg)
        perplexity = float("nan")
        epoch = 0
        while epoch < epochs:
            self.run_epoch(epoch, logger)
            if guard is not None:
                # Judge BEFORE eval/save: an anomalous state must neither
                # reach the checkpoint directory nor count as a good
                # epoch; all processes compute the identical verdict.
                cost = (
                    float(self.last_cost)
                    if self.last_cost is not None
                    else float("nan")
                )
                kind = guard.classify(cost, costs=self._epoch_costs)
                if kind is not None:
                    self._anomaly_rollback(guard, kind, epoch)
                    continue  # retry this epoch index on the next window
                guard.record(cost)
            self.metrics.counter("epochs_total").inc()
            # EVERY process runs the eval — it is a global-mesh computation
            # (GSPMD may partition it with collectives), so a chief-only
            # dispatch would hang or die once non-chief processes move on
            # (cost a real multi-host debugging cycle); only the chief
            # logs and records it.
            perplexity = self.evaluate("validation")
            if self.is_chief:
                logger.log_epoch_metric("Test-Perplexity", perplexity)
                if self.summary_writer is not None:
                    self.summary_writer.add_scalar(
                        "perplexity", perplexity, self.global_step
                    )
                self.history.append(
                    {
                        "epoch": epoch + 1,
                        "perplexity": perplexity,
                        "step": self.global_step,
                    }
                )
            if self.supervisor is not None:
                # Epoch boundary = demonstrable progress: bump the heartbeat
                # progress counter before the (possibly slow) save so the
                # elastic agent's stall clock resets on real forward motion.
                self.supervisor.report_progress(self.global_step)
                self.supervisor.save(
                    self.state, self.global_step, layout=self._layout_meta()
                )
                if self.supervisor.should_stop:
                    break
            epoch += 1
        final_cost = (
            float(self.last_cost) if self.last_cost is not None else float("nan")
        )
        if self.is_chief:
            logger.log_final(cost=final_cost)
            if self.summary_writer is not None:
                self.summary_writer.flush()
            self.metrics.flush_to(self.journal, component="lm_trainer")
            self.journal.flush()
        return {
            "perplexity": perplexity,
            "final_cost": final_cost,
            "global_step": self.global_step,
        }
