"""Resilience layer: durable checkpoints, preemption, anomaly rollback.

SURVEY.md §5 names fault tolerance as the reference's weakest layer — the
TF1 suite configured no saver at all, and a dead worker hung its chief's
gRPC calls forever. Rounds 1-5 upgraded that to heartbeats + orbax
checkpoints + a failure-reactive Supervisor stop; this module closes the
three gaps that remained between "stops cleanly" and "survives":

- **Durable checkpoints** — every ``step_N`` save commits a manifest
  sidecar (``step_N.manifest.json``, written atomically via tmp +
  ``os.replace``) carrying a per-leaf CRC32C of the in-memory state plus
  per-file size/CRC records of everything orbax put on disk. A checkpoint
  without a verifying manifest is *known-bad* and restore falls back to
  the newest step that verifies (``Supervisor.prepare_or_restore``);
  checkpoints predating the manifest (rounds ≤5) restore as before.
  CRC32C rides the native runtime's fast path
  (``runtime/native.py::crc32c``, the same C kernel the tfevents writer
  uses) with the pure table fallback from ``utils/summary.py``.

- **Preemption** — :func:`preemption_guard` installs SIGTERM/SIGINT
  handlers that flip ``Supervisor.request_stop``, so both trainers exit
  their epoch/dispatch loop at the next boundary *with a final save* —
  the TPU-pod preemption contract (the scheduler SIGTERMs, you get a
  grace window, you checkpoint and exit 0). A second signal restores the
  previous disposition, so a stuck run can still be killed.

- **Anomaly guard + rollback** — :class:`AnomalyGuard` watches per-epoch
  cost for NaN/inf and for spikes against a trailing window (the failure
  mode that dominates real LM runs; PaLM's spike protocol: restore the
  last good checkpoint and skip the offending data window). The trainers
  restore the newest *valid* checkpoint, leave the host data stream where
  it is (the offending epoch's draws are consumed, never replayed — that
  IS the skip), and retry up to ``max_rollbacks`` times, emitting a
  structured ``Rollback:`` log line and a ``rollback`` tfevents scalar
  per event.

Checkpoint I/O additionally gets bounded retry-with-backoff
(:func:`retry_io`) — a transient filesystem hiccup should cost a retry,
not the run.

No reference analog for any of this (the reference's fault story was
"don't crash"); the contracts are documented in docs/resilience.md and
proven by tests/test_resilience.py + the SIGTERM case in
tests/integration/test_fault_injection.py.
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import threading
import time

import numpy as np

from distributed_tensorflow_tpu.train import failpoints

MANIFEST_FORMAT = "dtf-checkpoint-manifest-v1"

# ---------------------------------------------------------------------------
# CRC32C — native fast path, pure-Python table fallback.
# ---------------------------------------------------------------------------

_crc_impl = None


def _crc32c_bytes(data: bytes) -> int:
    """CRC32C of a byte string: the native runtime's C kernel when the
    library loads (runtime/csrc/dtf_runtime.cc — same code path the
    tfevents TFRecord framing uses), else the pure-Python table from
    utils/summary.py. Both produce identical values (pinned by
    tests/test_runtime_native.py), so manifests written with one verify
    with the other."""
    global _crc_impl
    if _crc_impl is None:
        try:
            from distributed_tensorflow_tpu.runtime.native import crc32c

            crc32c(b"probe")  # force the library load now
            _crc_impl = crc32c
        except (ImportError, OSError):
            from distributed_tensorflow_tpu.utils.summary import crc32c

            _crc_impl = crc32c
    return _crc_impl(data)


_buf_impl = None


def crc32c_array(a) -> int:
    """CRC32C of an array's buffer (row-major). Accepts anything numpy can
    view — device arrays fetch to host here, which doubles as the save
    barrier for the leaf being checksummed. Uses the native zero-copy
    buffer kernel when available (runtime/native.py::crc32c_buffer)."""
    global _buf_impl
    host = np.ascontiguousarray(np.asarray(a))
    if _buf_impl is None:
        try:
            from distributed_tensorflow_tpu.runtime.native import crc32c_buffer

            crc32c_buffer(np.zeros(1, np.uint8))  # force the library load
            _buf_impl = crc32c_buffer
        except (ImportError, OSError):
            _buf_impl = lambda arr: _crc32c_bytes(arr.tobytes())  # noqa: E731
    return _buf_impl(host)


def crc32c_file(path: str) -> int:
    with open(path, "rb") as f:
        return _crc32c_bytes(f.read())


# ---------------------------------------------------------------------------
# Manifest write / verify.
# ---------------------------------------------------------------------------


def write_json_atomic(path: str, obj: dict) -> None:
    """Atomic JSON write (tmp name + ``os.replace``): a reader never sees
    a torn file, and a writer killed mid-write leaves only a ``.tmp``.
    THE crash-consistency primitive — the checkpoint manifests, the
    layout sidecars (train/supervisor.py), and the serving fleet's
    mailbox (serve_fleet.py) all write through here; a future hardening
    (fsync-before-replace, tmp collision handling) lands once.

    Failpoints (round 19): ``atomic.write`` at entry (+ tear of the
    committed file), ``atomic.write.commit`` between the tmp write and
    the replace — a kill there is the writer-crash case, leaving only a
    ``.tmp`` orphan for :func:`sweep_tmp_orphans`."""
    failpoints.fire("atomic.write")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
    failpoints.fire("atomic.write.commit")
    os.replace(tmp, path)
    failpoints.tear("atomic.write", path)


def sweep_tmp_orphans(
    dirpath: str, *, age_s: float = 60.0, now=None
) -> list[str]:
    """Remove stale ``.tmp`` orphans left by writers killed mid-write
    (the atomic-write protocol's one litter mode: the tmp file of a
    crashed process is never replaced away). Age-guarded — only files
    whose mtime is older than ``age_s`` go, so an in-flight write from a
    live process is never swept. Returns the removed paths. Both
    filesystem mailboxes (``DeltaExchange``, ``MailboxClient``) call this
    on construction and from their GC passes (round-19 satellite)."""
    removed: list[str] = []
    cutoff = (time.time() if now is None else now) - age_s
    try:
        names = os.listdir(dirpath)
    except OSError:
        return removed
    for name in names:
        if ".tmp" not in name:
            continue
        p = os.path.join(dirpath, name)
        try:
            if os.path.getmtime(p) <= cutoff and os.path.isfile(p):
                os.remove(p)
                removed.append(p)
        except OSError:
            continue  # racing writer committed/removed it — fine
    return removed


def manifest_path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(checkpoint_dir, f"step_{step}.manifest.json")


def leaf_checksums(state) -> tuple[dict, bool]:
    """Per-leaf CRC32C of a state pytree: ``{keystr: {crc32c, shape,
    dtype}}``. Leaves that are not fully addressable from this process
    (multi-host shards) are skipped — the second return value is False
    when any were, so verification knows the leaf map is partial (the
    per-file records still cover the bytes on disk)."""
    import jax.tree_util as jtu

    leaves: dict = {}
    complete = True
    for kp, leaf in jtu.tree_flatten_with_path(state)[0]:
        if not getattr(leaf, "is_fully_addressable", True):
            complete = False
            continue
        arr = np.asarray(leaf)
        leaves[jtu.keystr(kp)] = {
            "crc32c": crc32c_array(arr),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
    return leaves, complete


def _file_records(root: str) -> dict:
    out: dict = {}
    for dirpath, _, files in os.walk(root):
        for fname in files:
            p = os.path.join(dirpath, fname)
            out[os.path.relpath(p, root)] = {
                "size": os.path.getsize(p),
                "crc32c": crc32c_file(p),
            }
    return out


def write_manifest(checkpoint_dir: str, step: int, state=None) -> dict:
    """Commit the durability record for ``step_N``: per-file size+CRC over
    everything orbax wrote, per-leaf CRCs of the in-memory state (when
    given), and the layout sidecar's CRC when present. Written to a tmp
    name then ``os.replace``d — the manifest's presence marks a fully
    committed checkpoint, so a crash mid-save leaves a step that restore
    classifies as unverified rather than silently trusting it.

    Failpoint ``ckpt.manifest``: fire at entry, tear of the committed
    manifest after — the torn-manifest schedule is the corruption-cascade
    scenario (restore must fall back to the newest verifying step)."""
    failpoints.fire("ckpt.manifest")
    step_dir = os.path.join(checkpoint_dir, f"step_{step}")
    manifest: dict = {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "files": _file_records(step_dir),
        "sidecars": {},
    }
    layout_side = os.path.join(checkpoint_dir, f"step_{step}.layout.json")
    if os.path.exists(layout_side):
        manifest["sidecars"][os.path.basename(layout_side)] = {
            "size": os.path.getsize(layout_side),
            "crc32c": crc32c_file(layout_side),
        }
    if state is not None:
        manifest["leaves"], manifest["leaves_complete"] = leaf_checksums(state)
    write_json_atomic(manifest_path(checkpoint_dir, step), manifest)
    failpoints.tear("ckpt.manifest", manifest_path(checkpoint_dir, step))
    return manifest


def load_manifest(checkpoint_dir: str, step: int) -> dict | None:
    """The committed manifest for ``step_N``, or None when absent
    (pre-round-6 checkpoint). A present-but-unparseable manifest raises
    ValueError — corruption of the durability record itself must be loud,
    the same contract as ``Supervisor.saved_layout``."""
    path = manifest_path(checkpoint_dir, step)
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return None
    except ValueError as exc:
        raise ValueError(f"corrupt checkpoint manifest {path}: {exc}") from exc


def verify_files(checkpoint_dir: str, step: int) -> bool | None:
    """Integrity of ``step_N``'s bytes on disk against its manifest.

    Returns True (verified), False (known-bad: missing/truncated/flipped
    file, or the manifest itself is corrupt), or None (no manifest —
    unverifiable, the pre-manifest era; callers decide whether to trust)."""
    try:
        manifest = load_manifest(checkpoint_dir, step)
    except ValueError:
        return False
    if manifest is None:
        return None
    step_dir = os.path.join(checkpoint_dir, f"step_{step}")
    for rel, rec in manifest.get("files", {}).items():
        p = os.path.join(step_dir, rel)
        if not os.path.isfile(p) or os.path.getsize(p) != rec["size"]:
            return False
        if crc32c_file(p) != rec["crc32c"]:
            return False
    for name, rec in manifest.get("sidecars", {}).items():
        p = os.path.join(checkpoint_dir, name)
        if not os.path.isfile(p) or os.path.getsize(p) != rec["size"]:
            return False
        if crc32c_file(p) != rec["crc32c"]:
            return False
    return True


def verify_leaves(state, manifest: dict) -> bool:
    """Recompute the restored state's per-leaf CRCs against the manifest.
    Catches corruption the file pass cannot see (a byte flip the storage
    layer absorbed into a valid-looking read) and skew between manifest
    and data. Leaves absent from a partial (multi-host) manifest pass."""
    recorded = manifest.get("leaves")
    if not recorded:
        return True
    import jax.tree_util as jtu

    for kp, leaf in jtu.tree_flatten_with_path(state)[0]:
        rec = recorded.get(jtu.keystr(kp))
        if rec is None:
            continue
        if not getattr(leaf, "is_fully_addressable", True):
            continue
        if crc32c_array(leaf) != rec["crc32c"]:
            return False
    return True


# ---------------------------------------------------------------------------
# Async checkpoint writer (round 22): the bounded background half of the
# zero-stall save pipeline.
# ---------------------------------------------------------------------------


class AsyncCheckpointWriter:
    """Depth-1 background checkpoint writer: the training loop hands a
    fully host-resident write closure to :meth:`submit` and dispatches
    the next epoch immediately; a single daemon thread serializes, CRCs,
    and commits exactly as the synchronous path would (the closure IS the
    synchronous path — state parity is by construction, pinned in
    tests/test_resilience.py).

    Bounds: at most ONE write in flight plus ONE queued; submitting while
    a write is queued-but-not-started REPLACES it (the superseded step
    never lands — on a writer slower than the save cadence, disk always
    receives the newest snapshot rather than an ever-growing backlog of
    stale ones; ``superseded`` counts the drops). :meth:`wait_pending`
    blocks until everything submitted has committed — the shutdown/final-
    save drain, and the barrier every restore entry point takes (an
    in-flight step directory has NO manifest yet, which reads as
    "unverifiable, trusted" to pre-manifest fallback logic; draining
    first keeps reads ordered after writes).

    A write that raises does not kill the writer: the error is captured
    and re-raised at the next :meth:`wait_pending` /
    :meth:`raise_deferred` — losing one save costs one checkpoint
    interval (the round-6 fallback contract), losing the ERROR would cost
    the diagnosis. Failpoint ``ckpt.async`` fires on the worker thread
    before each queued write executes (``raise`` = writer dies before
    serializing, the queued step never lands; ``kill`` = the crash-mid-
    async-write case, indistinguishable from a torn synchronous write by
    design; ``delay`` makes supersession deterministic in tests)."""

    def __init__(self, *, name: str = "ckpt-writer"):
        self._cond = threading.Condition()
        self._pending = None  # (tag, fn) queued, not yet started
        self._in_flight = False
        self._error: BaseException | None = None
        self._closed = False
        self.superseded = 0
        self._thread = threading.Thread(
            target=self._worker, name=name, daemon=True
        )
        self._thread.start()

    def submit(self, fn, *, tag=None) -> None:
        """Queue ``fn`` for the worker. Replaces a queued-not-started
        write (the newer snapshot supersedes); never blocks on I/O."""
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            if self._pending is not None:
                self.superseded += 1
            self._pending = (tag, fn)
            self._cond.notify_all()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                if self._pending is None:
                    return
                _tag, fn = self._pending
                self._pending = None
                self._in_flight = True
            try:
                failpoints.fire("ckpt.async")
                fn()
            except BaseException as exc:  # noqa: BLE001 — deferred re-raise
                with self._cond:
                    self._error = exc
            finally:
                with self._cond:
                    self._in_flight = False
                    self._cond.notify_all()

    def raise_deferred(self) -> None:
        """Re-raise (and clear) a captured writer error; non-blocking."""
        with self._cond:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def wait_pending(self) -> None:
        """Block until every submitted write has committed, then surface
        any deferred writer error."""
        with self._cond:
            while self._pending is not None or self._in_flight:
                self._cond.wait()
        self.raise_deferred()

    @property
    def busy(self) -> bool:
        with self._cond:
            return self._pending is not None or self._in_flight

    def close(self) -> None:
        """Drain and stop the worker thread."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self.wait_pending()
        self._thread.join(timeout=30)


# ---------------------------------------------------------------------------
# Progress heartbeat + stall dump (round 22): the watchdog's worker half.
# ---------------------------------------------------------------------------


def touch_heartbeat(path: str) -> bool:
    """Atomic mtime-bump of a worker heartbeat file (creating it on the
    first beat). The elastic watchdog reads the mtime age — an mtime
    update is a single metadata write, so there is no torn-read mode and
    nothing to fsync. Returns False (never raises) on I/O failure: a
    heartbeat must not be able to kill the run it protects."""
    if not path:
        return False
    try:
        os.utime(path)
        return True
    except FileNotFoundError:
        try:
            with open(path, "a", encoding="utf-8"):
                pass
            return True
        except OSError:
            return False
    except OSError:
        return False


_stall_dump_file = None  # keep the fd alive — faulthandler borrows it


def arm_stall_dump(path: str | None = None) -> str | None:
    """Register a ``faulthandler`` traceback dump on SIGUSR1, appended to
    ``path`` (default: ``$DTF_STALL_DUMP``; unset/empty = disarmed).
    The elastic watchdog sends SIGUSR1 right before SIGKILLing a stalled
    member, so the member's own all-thread stacks land in the logdir for
    diagnosis. faulthandler registers a C-level handler — a worker wedged
    inside a collective CAN still dump; a SIGSTOPped one cannot (the
    signal queues until SIGCONT), which is why the dump is best-effort
    and the verdict never waits on it. Returns the armed path or None."""
    global _stall_dump_file
    if path is None:
        path = os.environ.get("DTF_STALL_DUMP", "")
    if not path:
        return None
    import faulthandler

    try:
        f = open(path, "a", encoding="utf-8")
        faulthandler.register(
            signal.SIGUSR1, file=f, all_threads=True, chain=False
        )
    except (OSError, ValueError, AttributeError):  # pragma: no cover
        return None  # exotic host (no SIGUSR1 / no fd) — stay disarmed
    _stall_dump_file = f
    return path


def disarm_stall_dump() -> None:
    """Unregister the SIGUSR1 dump handler and close its file. Safe to
    call when never armed (workers call it from teardown paths)."""
    global _stall_dump_file
    import faulthandler

    try:
        faulthandler.unregister(signal.SIGUSR1)
    except (ValueError, AttributeError):  # pragma: no cover - no SIGUSR1
        pass
    if _stall_dump_file is not None:
        try:
            _stall_dump_file.close()
        except OSError:  # pragma: no cover
            pass
        _stall_dump_file = None


# ---------------------------------------------------------------------------
# Bounded retry with exponential backoff — the ONE retry implementation.
# Checkpoint I/O (retry_io), the elastic gang-restart cycle
# (train/elastic.py), and the bounded jax.distributed bootstrap
# (cluster.bounded_initialize) all go through here: one backoff state
# machine to test, not three near-copies to drift.
# ---------------------------------------------------------------------------


def backoff_delay(
    attempt: int,
    *,
    backoff: float,
    max_backoff: float = 30.0,
    jitter: float = 0.0,
    rng=None,
) -> float:
    """The one backoff formula: delay before retry ``attempt + 1`` is
    ``min(backoff * 2**attempt, max_backoff)``, multiplied by
    ``1 + jitter*u`` with ``u`` uniform in [0, 1). Shared by
    :func:`retry` (checkpoint I/O, the elastic gang cycle) and the
    serving fleet's per-replica relaunch scheduler (serve_fleet.py),
    which cannot use :func:`retry` directly — its members restart
    INDEPENDENTLY while the rest of the fleet keeps serving, so there is
    no single call to wrap."""
    if rng is None:
        import random as _random

        rng = _random
    delay = min(backoff * (2**attempt), max_backoff)
    if jitter:
        delay *= 1.0 + jitter * rng.random()
    return delay


def retry(
    fn,
    *,
    attempts: int = 3,
    backoff: float = 0.25,
    max_backoff: float = 30.0,
    jitter: float = 0.0,
    retry_on: tuple = (OSError,),
    describe: str = "operation",
    on_retry=None,
    sleep=time.sleep,
    rng=None,
):
    """Run ``fn`` with bounded retry + exponential backoff. The last failure
    re-raises — resilience means surviving a hiccup, not silently swallowing
    a dead disk (or a gang that can never come up).

    Delay before attempt ``k+1`` is ``min(backoff * 2**k, max_backoff)``,
    multiplied by ``1 + jitter*u`` with ``u`` uniform in [0, 1) — jitter
    de-synchronizes a gang of agents all restarting off the same failure so
    their rendezvous attempts don't thundering-herd the coordinator.
    ``on_retry(exc, attempt, delay)`` fires before each sleep (the elastic
    agent's ``Restart:`` line + tfevents scalar hang off it); ``sleep`` and
    ``rng`` are injectable so the state machine tests run without wall time.
    """
    last = None
    for attempt in range(max(1, attempts)):
        try:
            return fn()
        except retry_on as exc:  # noqa: PERF203 — retry loop by design
            last = exc
            if attempt + 1 >= attempts:
                raise
            delay = backoff_delay(
                attempt,
                backoff=backoff,
                max_backoff=max_backoff,
                jitter=jitter,
                rng=rng,
            )
            if on_retry is not None:
                on_retry(exc, attempt, delay)
            sleep(delay)
    raise last  # pragma: no cover — unreachable (loop raises)


def retry_io(
    fn,
    *,
    attempts: int = 3,
    backoff: float = 0.25,
    retry_on: tuple = (OSError,),
    describe: str = "checkpoint I/O",
    jitter: float = 0.0,
    rng=None,
    sleep=time.sleep,
):
    """Checkpoint-I/O flavor of :func:`retry` (kept as the narrow public
    surface Supervisor uses; jitter defaults OFF — a single process
    retrying its own disk has nothing to de-synchronize from — but when
    enabled it takes the same seeded ``rng`` and injectable ``sleep`` as
    :func:`retry`, so chaos-sweep retry timing is reproducible)."""
    return retry(
        fn,
        attempts=attempts,
        backoff=backoff,
        retry_on=retry_on,
        describe=describe,
        jitter=jitter,
        rng=rng,
        sleep=sleep,
    )


# ---------------------------------------------------------------------------
# Preemption: SIGTERM/SIGINT → request_stop → boundary exit + final save.
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def preemption_guard(
    supervisor, *, enabled: bool = True, print_fn=print, journal=None
):
    """Install SIGTERM/SIGINT handlers for the duration of a training run:
    the first signal flips ``supervisor.request_stop()`` (the loop exits
    at the next epoch/dispatch boundary, whose save makes the final
    checkpoint) and immediately restores the previous handlers, so a
    second signal falls through to the old disposition (default: die) —
    graceful first, killable always. The ``Preemption:`` line is a
    lifecycle event (round 10): journaled through ``journal`` (or the
    process default) and rendered byte-identically to stdout.

    No-ops (yields None) when disabled, when there is no supervisor to
    stop, or off the main thread (CPython only delivers signals there) —
    but the off-main-thread case is the one a caller did NOT choose, so
    it emits one structured ``Preemption: disarmed (non-main thread)``
    line (round 22): a guard that never armed is visible in the journal
    instead of discovered at kill time.

    Round 22: the first signal additionally triggers
    ``supervisor.emergency_save()`` when the supervisor has one — the
    last completed-epoch host snapshot (retained by the async checkpoint
    pipeline) persists immediately, so a preemption landing mid-epoch
    loses nothing; the ``Preemption:`` line grows ``saved_step=N`` when
    a step was persisted (absent otherwise — the default line is
    byte-identical to round 6)."""
    if not enabled or supervisor is None:
        yield None
        return
    if threading.current_thread() is not threading.main_thread():
        from distributed_tensorflow_tpu.observability import format as obs_format
        from distributed_tensorflow_tpu.observability import (
            journal as obs_journal,
        )

        j = journal if journal is not None else obs_journal.get_journal()
        obs_format.emit_line(
            "preemption",
            journal=j,
            print_fn=print_fn,
            disarmed="non-main thread",
        )
        yield None
        return
    prev: dict = {}

    def _restore():
        while prev:
            sig, old = prev.popitem()
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):  # pragma: no cover
                pass

    pending: list[dict] = []

    def _handler(signum, frame):
        supervisor.request_stop()
        # Emergency snapshot (round 22): persist the last completed-epoch
        # host state NOW, not at the boundary the loop may never reach in
        # the grace window. emergency_save is reentrancy-guarded (no-op
        # when the signal interrupted a save already in progress) and
        # quiet (zero journal/metrics I/O in this frame); it returns the
        # persisted step, or None when there was nothing newer than disk.
        saved_step = None
        emergency = getattr(supervisor, "emergency_save", None)
        if emergency is not None:
            try:
                saved_step = emergency()
            except Exception:  # noqa: BLE001 — best-effort in a handler
                saved_step = None
        # Structured one-liner (greppable key=value, like Step:/Cost:).
        # Journal file I/O is NOT reentrancy-safe: the signal can land
        # mid-write on the journal's own buffered file (StepLogger emits
        # on every step line), and a second write from the handler would
        # raise "reentrant call" INTO the training loop — killing the run
        # the guard exists to stop gracefully. So the handler builds and
        # prints the event with zero I/O (NullJournal) and defers the
        # real journal write to guard exit, after the loop has stopped.
        from distributed_tensorflow_tpu.observability import format as obs_format
        from distributed_tensorflow_tpu.observability.journal import NullJournal

        extra = {} if saved_step is None else {"saved_step": int(saved_step)}
        ev = obs_format.emit_line(
            "preemption",
            journal=NullJournal(),
            print_fn=print_fn,
            signal=int(signum),
            **extra,
        )
        pending.append(
            {k: v for k, v in ev.items() if k not in ("ts", "kind")}
        )
        _restore()

    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, _handler)
            except (ValueError, OSError):  # pragma: no cover — exotic hosts
                pass
        yield _handler
    finally:
        _restore()
        if pending:
            from distributed_tensorflow_tpu.observability import (
                journal as obs_journal,
            )

            j = journal if journal is not None else obs_journal.get_journal()
            for fields in pending:
                j.emit("preemption", **fields)


# ---------------------------------------------------------------------------
# Anomaly guard (NaN/inf + spike-vs-trailing-window) for the epoch loop.
# ---------------------------------------------------------------------------


class AnomalyError(RuntimeError):
    """Anomalous cost with no rollback budget (or no checkpoint) left."""


class AnomalyGuard:
    """Per-epoch cost monitor. ``classify`` returns ``"nan"`` for any
    non-finite cost in the epoch, ``"spike"`` when the epoch cost exceeds
    ``spike_threshold ×`` the median of the last ``window`` *good* epochs
    (only after a full window of history — early-training descent must
    not trip it), else None. ``record`` feeds the trailing window; only
    epochs that passed get recorded, so one spike does not poison the
    baseline that judges the retry."""

    def __init__(
        self,
        *,
        window: int = 8,
        spike_threshold: float = 3.0,
        max_rollbacks: int = 3,
    ):
        self.window = max(1, int(window))
        self.spike_threshold = float(spike_threshold)
        self.max_rollbacks = int(max_rollbacks)
        self.history: list[float] = []
        self.rollbacks = 0

    @classmethod
    def from_config(cls, config) -> "AnomalyGuard | None":
        """The TrainConfig surface: ``max_rollbacks=0`` disables the guard
        entirely; ``spike_threshold=0`` keeps only the NaN/inf check."""
        if not getattr(config, "max_rollbacks", 0):
            return None
        return cls(
            window=config.anomaly_window,
            spike_threshold=config.spike_threshold,
            max_rollbacks=config.max_rollbacks,
        )

    def classify(self, cost: float, costs=None) -> str | None:
        vals = np.asarray(costs if costs is not None else [cost], np.float64)
        if not np.all(np.isfinite(vals)) or not np.isfinite(cost):
            return "nan"
        if self.spike_threshold > 0 and len(self.history) >= self.window:
            ref = float(np.median(self.history[-self.window :]))
            if ref > 0 and cost > self.spike_threshold * ref:
                return "spike"
        return None

    def record(self, cost: float) -> None:
        self.history.append(float(cost))

    @property
    def exhausted(self) -> bool:
        return self.rollbacks >= self.max_rollbacks
