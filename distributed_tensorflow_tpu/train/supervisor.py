"""Supervisor: chief election, init-or-restore, orderly shutdown (C13).

The reference's ``tf.train.Supervisor`` (reference tfdist_between.py:78,83)
provided: chief election (``is_chief = task_index == 0``), chief-only variable
init with non-chiefs waiting for an initialized model, session recovery for
restarted workers, and orderly stop (``sv.request_stop()`` / ``sv.stop()``,
reference tfdist_between_sync.py:120-123).

TPU-native mapping: there are no sessions to recover — state is an explicit
pytree. "Prepare or wait" becomes *restore-or-init* against a checkpoint
directory (a deliberate upgrade: the reference configured no saver at all,
SURVEY.md §5 "Checkpoint/resume"), and cross-process agreement comes from
``jax.distributed``'s coordination barrier plus every process computing the
same deterministic init (same seed ⇒ same params, no broadcast needed).
Checkpointing is orbax-backed, async-capable, and sharding-aware.
"""

from __future__ import annotations

import json
import os
import re

import jax

from distributed_tensorflow_tpu.parallel.strategy import TrainState

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False

_STEP_DIR = re.compile(r"^step_(\d+)$")


def latest_checkpoint_step(checkpoint_dir: str | None) -> int | None:
    """Newest ``step_N`` under ``checkpoint_dir``, or None. Read-only probe —
    never creates the directory (unlike constructing a Supervisor)."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return None
    steps = [
        int(m.group(1))
        for d in os.listdir(checkpoint_dir)
        if (m := _STEP_DIR.match(d))
    ]
    return max(steps) if steps else None


class Supervisor:
    def __init__(self, *, is_chief: bool = True, checkpoint_dir: str | None = None):
        self.is_chief = is_chief
        self.checkpoint_dir = os.path.abspath(checkpoint_dir) if checkpoint_dir else None
        self._stop_requested = False
        self._heartbeat = None
        self._ckptr = None
        if self.checkpoint_dir and _HAVE_ORBAX:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            self._ckptr = ocp.StandardCheckpointer()

    def attach_heartbeat(self, heartbeat) -> None:
        """Arm failure-reactive stopping: when the attached
        HeartbeatCoordinator (runtime/native.py) reports a failed worker,
        ``should_stop`` turns true — so the chief's training loop exits at
        the next epoch boundary with checkpoints intact, instead of hanging
        in a collective the dead worker will never join (the reference's
        failure mode: gRPC calls blocking forever, SURVEY.md §5)."""
        self._heartbeat = heartbeat

    # -- checkpoint/restore (upgrade over the reference's nothing) --------

    def latest_step(self) -> int | None:
        return latest_checkpoint_step(self.checkpoint_dir)

    def save(
        self, state: TrainState, step: int, layout: dict | None = None
    ) -> None:
        """Chief-only checkpoint write (non-chiefs no-op, as with the
        reference's chief-owned init/teardown duties). ``layout`` is an
        optional topology descriptor (mode, pipeline stages, async
        replicas — see LMTrainer._layout_meta) written as a JSON sidecar
        ``step_N.layout.json``; cross-topology restore reads it to know
        which canonicalization the saved arrays need."""
        if not (self.is_chief and self._ckptr):
            return
        path = os.path.join(self.checkpoint_dir, f"step_{step}")
        self._ckptr.save(path, state, force=True)
        self._ckptr.wait_until_finished()
        if layout is not None:
            side = f"{path}.layout.json"
            tmp = f"{side}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(layout, f)
            os.replace(tmp, side)

    def saved_layout(self, step: int) -> dict | None:
        """The layout sidecar written alongside ``step_N``, or None
        (pre-round-5 checkpoints have none — callers must treat that as
        "same layout as mine", the old behavior)."""
        if not self.checkpoint_dir:
            return None
        try:
            with open(
                os.path.join(self.checkpoint_dir, f"step_{step}.layout.json")
            ) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def restore_raw(self, step: int, abstract):
        """Restore ``step_N`` against an explicit abstract pytree (shapes/
        dtypes of the SOURCE layout) — the cross-topology path: the caller
        canonicalizes the result rather than assuming it matches its own
        state's shapes the way :meth:`prepare_or_restore` does."""
        if self._ckptr is None:
            raise RuntimeError("no checkpointer (orbax unavailable or no dir)")
        path = os.path.join(self.checkpoint_dir, f"step_{step}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, abstract)
        return self._ckptr.restore(path, abstract)

    def prepare_or_restore(self, state: TrainState) -> tuple[TrainState, int]:
        """Restore-or-init: the analog of ``prepare_or_wait_for_session``.

        Returns (state, start_step). With no checkpoint present, the passed-in
        freshly-initialized state is returned — every process computed the
        identical init from the shared seed, which is how "non-chief waits for
        chief's init" degenerates on a deterministic SPMD system.
        """
        step = self.latest_step()
        if step is None or self._ckptr is None:
            return state, 0
        path = os.path.join(self.checkpoint_dir, f"step_{step}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
        restored = self._ckptr.restore(path, abstract)
        return restored, step

    # -- orderly shutdown (reference sv.request_stop/sv.stop) -------------

    def request_stop(self) -> None:
        self._stop_requested = True

    @property
    def should_stop(self) -> bool:
        if self._stop_requested:
            return True
        if self._heartbeat is not None and self._heartbeat.failed_count() > 0:
            self._stop_requested = True
        return self._stop_requested

    def stop(self) -> None:
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
        self._stop_requested = True
