"""Supervisor: chief election, init-or-restore, orderly shutdown (C13).

The reference's ``tf.train.Supervisor`` (reference tfdist_between.py:78,83)
provided: chief election (``is_chief = task_index == 0``), chief-only variable
init with non-chiefs waiting for an initialized model, session recovery for
restarted workers, and orderly stop (``sv.request_stop()`` / ``sv.stop()``,
reference tfdist_between_sync.py:120-123).

TPU-native mapping: there are no sessions to recover — state is an explicit
pytree. "Prepare or wait" becomes *restore-or-init* against a checkpoint
directory (a deliberate upgrade: the reference configured no saver at all,
SURVEY.md §5 "Checkpoint/resume"), and cross-process agreement comes from
``jax.distributed``'s coordination barrier plus every process computing the
same deterministic init (same seed ⇒ same params, no broadcast needed).
Checkpointing is orbax-backed, async-capable, and sharding-aware.

Round 6 makes the checkpoints *durable* (train/resilience.py): every save
commits a CRC32C manifest sidecar, restore verifies and falls back to the
newest VALID step when the latest is corrupt or partial, checkpoint I/O
retries with backoff, and a retention policy (``keep_last_n``) GCs old
steps without ever removing the last verified one. Contracts in
docs/resilience.md.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import shutil
import threading
import warnings

from typing import TYPE_CHECKING

from distributed_tensorflow_tpu.train import resilience

if TYPE_CHECKING:  # jax-backed; the probe half of this module is file I/O
    from distributed_tensorflow_tpu.parallel.strategy import TrainState

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False

_STEP_DIR = re.compile(r"^step_(\d+)$")

# Layout-sidecar keys that describe the saved state's SHAPES (which
# canonicalization a cross-topology restore needs). Everything else in the
# sidecar is restore POLICY — e.g. round 8's "world"/"global_batch", which
# the elastic resize path reads to preserve the global batch across a
# world-size change — and must not break same-layout compatibility checks.
# Round 17: "delta_dtype"/"overlap" are SHAPE keys — the compressed-delta
# residual and the in-flight delta are extra pytree nodes in DiLoCoState,
# so a checkpoint written with a lever on has a different structure than
# one without (the keys are only present when the lever is on, so old
# sidecars keep comparing equal to lever-off metas).
LAYOUT_SHAPE_KEYS = ("mode", "replicas", "stages", "delta_dtype", "overlap")


def layout_shape(layout: dict | None) -> dict:
    """The shape-determining slice of a checkpoint layout sidecar (see
    :data:`LAYOUT_SHAPE_KEYS`): what trainers compare to decide between
    the bitwise same-layout restore and the canonical cross-topology
    path. An old sidecar (no policy keys) and a round-8 one with
    identical topology compare equal here by construction."""
    return {
        k: v for k, v in (layout or {}).items() if k in LAYOUT_SHAPE_KEYS
    }


def checkpoint_steps(checkpoint_dir: str | None) -> list[int]:
    """All ``step_N`` under ``checkpoint_dir``, ascending. Read-only."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return []
    return sorted(
        int(m.group(1))
        for d in os.listdir(checkpoint_dir)
        if (m := _STEP_DIR.match(d))
    )


def latest_checkpoint_step(
    checkpoint_dir: str | None, *, verify: bool = False
) -> int | None:
    """Newest ``step_N`` under ``checkpoint_dir``, or None. Read-only probe —
    never creates the directory (unlike constructing a Supervisor).

    ``verify=True`` returns the newest step whose bytes on disk pass the
    manifest integrity check (train/resilience.py) — skipping corrupt or
    partially written checkpoints AND pre-manifest ones (no manifest means
    nothing to verify against; use the default probe to see those)."""
    steps = checkpoint_steps(checkpoint_dir)
    if not verify:
        return steps[-1] if steps else None
    for step in reversed(steps):
        if resilience.verify_files(checkpoint_dir, step) is True:
            return step
    return None


class Supervisor:
    def __init__(
        self,
        *,
        is_chief: bool = True,
        checkpoint_dir: str | None = None,
        keep_last_n: int | None = None,
        io_retries: int = 3,
        io_backoff: float = 0.25,
        async_checkpoint: bool = False,
    ):
        self.is_chief = is_chief
        self.checkpoint_dir = os.path.abspath(checkpoint_dir) if checkpoint_dir else None
        self.keep_last_n = keep_last_n
        self.io_retries = max(1, int(io_retries))
        self.io_backoff = float(io_backoff)
        # Async checkpoint pipeline (round 22): ``save`` snapshots device
        # state to host and returns immediately; a depth-1 background
        # writer commits the EXACT synchronous byte sequence. Default OFF
        # here (bare Supervisors — inference, serving, launch probes —
        # have no training loop to unblock); TrainConfig.async_checkpoint
        # (default ON) flips it for the trainers.
        self.async_checkpoint = bool(async_checkpoint)
        self._writer = None
        self._write_lock = threading.Lock()
        self._saving = False  # main-thread sync save in progress
        self._last_snapshot = None  # (host_state, step, layout) — newest
        self._heartbeat_file = os.environ.get("DTF_HEARTBEAT_FILE") or None
        self._stop_requested = False
        self._heartbeat = None
        self._stall_timeout_ms = 0
        self._progress_fn = None
        self._ckptr = None
        self._journal = None
        self._metrics = None
        self._spans = None
        if self.checkpoint_dir and _HAVE_ORBAX:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            self._ckptr = ocp.StandardCheckpointer()

    def attach_observability(
        self, journal=None, metrics=None, spans=None
    ) -> None:
        """Arm checkpoint telemetry (round 10): each save/restore emits a
        ``checkpoint_save``/``checkpoint_restore`` journal event (step,
        bytes, duration), feeds the metrics registry (save count/bytes/
        duration histogram), and records a host span. All three sinks are
        optional — trainers wire theirs in; a bare Supervisor stays
        silent. Trace ids (round 12) need no plumbing here: saves happen
        inside the trainer's ambient trace context, so the journal tags
        every checkpoint event with the run's trace automatically
        (observability/tracing.py)."""
        self._journal = journal
        self._metrics = metrics
        self._spans = spans

    def _span(self, name: str, **args):
        import contextlib

        if self._spans is None:
            return contextlib.nullcontext()
        return self._spans.span(name, cat="checkpoint", **args)

    def attach_heartbeat(self, heartbeat, *, stall_timeout_ms: int = 0) -> None:
        """Arm failure-reactive stopping: when the attached
        HeartbeatCoordinator (runtime/native.py) reports a failed worker,
        ``should_stop`` turns true — so the chief's training loop exits at
        the next epoch boundary with checkpoints intact, instead of hanging
        in a collective the dead worker will never join (the reference's
        failure mode: gRPC calls blocking forever, SURVEY.md §5).

        ``stall_timeout_ms > 0`` (round 7) additionally trips the stop when
        a worker is LIVE-BUT-STALLED — beating, but its progress counter
        frozen past the window (``HeartbeatCoordinator.stalled_count``) —
        the failure mode silence timeouts can never see."""
        self._heartbeat = heartbeat
        self._stall_timeout_ms = int(stall_timeout_ms)

    def attach_progress(self, progress_fn) -> None:
        """Wire the heartbeat progress reporter (typically
        ``ProcessContext.report_progress``): trainers call
        :meth:`report_progress` with the global step at epoch boundaries,
        and the counter rides every outgoing beat so the detector — chief-
        or agent-hosted — can tell stalled from dead."""
        self._progress_fn = progress_fn

    def report_progress(self, progress: int) -> None:
        """Advance the attached heartbeat progress counter; no-op when no
        reporter is wired (single process, heartbeat unavailable).

        Round 22: when ``$DTF_HEARTBEAT_FILE`` names a path (the elastic
        launcher exports one per worker), each report also mtime-bumps
        that file and emits a ``heartbeat`` journal event — the progress
        watchdog's evidence that this member is alive AND advancing, not
        merely scheduled. Gated on the env var so default journal streams
        are byte-identical to round 21."""
        if self._progress_fn is not None:
            self._progress_fn(int(progress))
        if self._heartbeat_file:
            resilience.touch_heartbeat(self._heartbeat_file)
            if self._journal is not None:
                self._journal.emit(
                    "heartbeat",
                    rank=int(os.environ.get("DTF_RANK", "0") or 0),
                    step=int(progress),
                )

    # -- checkpoint/restore (upgrade over the reference's nothing) --------

    def latest_step(self, *, verify: bool = False) -> int | None:
        self.wait_pending()
        return latest_checkpoint_step(self.checkpoint_dir, verify=verify)

    def newest_restorable_step(self) -> int | None:
        """Newest step that is not KNOWN-bad: manifest-verified where a
        manifest exists, trusted where none does (pre-round-6 checkpoints
        carry no manifest but must keep restoring). The restore entry
        points use this so a corrupt latest checkpoint points them at the
        newest valid one instead.

        Reads drain writes (round 22): an in-flight async step directory
        has no manifest yet — ``verify_files`` would return None and this
        probe would TRUST a half-written step — so every restore entry
        point drains the writer first."""
        self.wait_pending()
        for step in reversed(checkpoint_steps(self.checkpoint_dir)):
            if resilience.verify_files(self.checkpoint_dir, step) is False:
                warnings.warn(
                    f"checkpoint step_{step} fails manifest verification; "
                    "falling back to the previous step",
                    RuntimeWarning,
                    stacklevel=2,
                )
                continue
            return step
        return None

    def _retry(self, fn, describe: str):
        return resilience.retry_io(
            fn,
            attempts=self.io_retries,
            backoff=self.io_backoff,
            describe=describe,
        )

    def save(
        self, state: TrainState, step: int, layout: dict | None = None
    ) -> None:
        """Chief-only checkpoint write (non-chiefs no-op, as with the
        reference's chief-owned init/teardown duties). ``layout`` is an
        optional topology descriptor (mode, pipeline stages, async
        replicas — see LMTrainer._layout_meta) written as a JSON sidecar
        ``step_N.layout.json``; cross-topology restore reads it to know
        which canonicalization the saved arrays need.

        Durability (round 6): the orbax write runs under bounded
        retry-with-backoff, then the manifest sidecar commits atomically
        (its presence marks a complete checkpoint), then the retention
        policy GCs steps beyond ``keep_last_n`` — never the last valid.

        Async (round 22, ``async_checkpoint=True``): the save boundary
        pays only the device→host snapshot; serialize+CRC+manifest+GC run
        on the background writer through the SAME ``_write_step`` the
        synchronous path uses, so artifacts are state-identical (test-
        pinned: byte-equal manifest leaf CRCs, bitwise-equal restores —
        orbax's own content-hashed filenames keep raw bytes
        nondeterministic even sync-vs-sync). The snapshot is retained as
        the emergency-save source; a
        prior writer error surfaces here (and at ``wait_pending``) rather
        than being swallowed."""
        if not (self.is_chief and self._ckptr):
            return
        resilience.failpoints.fire("ckpt.save")
        if self.async_checkpoint:
            import jax
            import numpy as _np

            # The snapshot must OWN its memory: on CPU backends
            # jax.device_get returns zero-copy VIEWS of the device
            # buffers, and a donated buffer is overwritten by the next
            # dispatched step while the write is still in flight (the
            # orbax bytes and the manifest CRCs would then disagree —
            # caught live by the corrupt-latest fallback test).
            host_state = jax.tree.map(
                lambda x: x.copy() if isinstance(x, _np.ndarray) else x,
                jax.device_get(state),
            )
            self._last_snapshot = (host_state, int(step), layout)
            if self._writer is None:
                self._writer = resilience.AsyncCheckpointWriter()
            else:
                self._writer.raise_deferred()
            self._writer.submit(
                lambda: self._write_step(host_state, int(step), layout),
                tag=int(step),
            )
            return
        self._saving = True
        try:
            self._write_step(state, int(step), layout)
        finally:
            self._saving = False

    def _write_step(
        self, state, step: int, layout: dict | None, *, quiet: bool = False
    ) -> None:
        """The one write sequence (round-6 order, both modes): orbax under
        retry → layout sidecar → manifest commit → telemetry → retention
        sweep. Runs on the main thread (sync) or the writer thread
        (async); ``_write_lock`` serializes the two. The sweep running
        HERE, after the manifest commit, is what keeps ``keep_last_n`` GC
        ordered behind every in-flight write — a step whose manifest
        isn't committed yet is never a sweep candidate's newest-valid
        competitor mid-write. ``quiet=True`` (emergency save from the
        signal-handler frame) skips span/journal/metrics — none of those
        sinks are reentrancy-safe there."""
        import time as _time

        path = os.path.join(self.checkpoint_dir, f"step_{step}")

        def _write():
            self._ckptr.save(path, state, force=True)
            self._ckptr.wait_until_finished()

        with self._write_lock:
            t0 = _time.perf_counter()
            span = (
                contextlib.nullcontext()
                if quiet
                else self._span("checkpoint_save", step=int(step))
            )
            with span:
                self._retry(_write, f"save step_{step}")
                if layout is not None:
                    resilience.write_json_atomic(f"{path}.layout.json", layout)
                manifest = self._retry(
                    lambda: resilience.write_manifest(
                        self.checkpoint_dir, step, state
                    ),
                    f"manifest step_{step}",
                )
            duration_s = _time.perf_counter() - t0
            # The manifest already walked the step dir with sizes — the byte
            # count is free (no second disk pass).
            nbytes = sum(
                r["size"] for r in manifest.get("files", {}).values()
            ) + sum(r["size"] for r in manifest.get("sidecars", {}).values())
            if not quiet and self._journal is not None:
                self._journal.emit(
                    "checkpoint_save",
                    step=int(step),
                    bytes=int(nbytes),
                    duration_s=round(duration_s, 6),
                )
            if not quiet and self._metrics is not None:
                self._metrics.counter("checkpoint_saves_total").inc()
                self._metrics.counter("checkpoint_bytes_total").inc(nbytes)
                self._metrics.histogram("checkpoint_save_s").observe(
                    duration_s
                )
            self._retention_sweep()

    def wait_pending(self) -> None:
        """Drain the async writer: every submitted write committed (or
        its deferred error re-raised). No-op in sync mode. The final-save
        barrier — trainers call it on run() exit — and the read barrier
        every restore entry point takes (an in-flight step directory has
        no manifest yet and would read as 'unverifiable, trusted')."""
        w = self._writer
        if w is not None:
            w.wait_pending()

    def emergency_save(self) -> int | None:
        """Persist the newest retained host snapshot NOW (the preemption
        handler's hook). Drains the writer first — normally that alone
        lands the newest step — then writes the snapshot synchronously
        only if it is still not committed on disk (superseded queue slot,
        or the writer died on it). Reentrancy-guarded: no-op (None) when
        the signal interrupted a synchronous save in progress (a blocking
        wait here would deadlock the main thread against itself).
        Returns the snapshot's step when it is durable on disk after the
        call, else None."""
        if not (self.is_chief and self._ckptr) or self._saving:
            return None
        snap = self._last_snapshot
        if snap is None:
            return None
        host_state, step, layout = snap
        try:
            self.wait_pending()
        except Exception:  # noqa: BLE001 — writer died; write it ourselves
            pass
        if resilience.verify_files(self.checkpoint_dir, step) is not True:
            try:
                self._write_step(host_state, step, layout, quiet=True)
            except Exception:  # noqa: BLE001 — best-effort in a handler
                return None
        return int(step)

    def _retention_sweep(self) -> None:
        """Delete steps beyond the ``keep_last_n`` newest. The newest
        VALID step is never deleted, even when it falls outside the
        window — if every kept step were corrupt, the sweep must not have
        destroyed the one that restores."""
        n = self.keep_last_n
        if not n or n < 1:
            return
        steps = checkpoint_steps(self.checkpoint_dir)
        doomed = steps[:-n]
        if not doomed:
            return
        kept_valid = any(
            resilience.verify_files(self.checkpoint_dir, s) is True
            for s in steps[-n:]
        )
        protected: set[int] = set()
        if not kept_valid:
            for s in reversed(doomed):
                if resilience.verify_files(self.checkpoint_dir, s) is True:
                    protected.add(s)
                    break
        for s in doomed:
            if s in protected:
                continue
            shutil.rmtree(
                os.path.join(self.checkpoint_dir, f"step_{s}"),
                ignore_errors=True,
            )
            for side in (f"step_{s}.layout.json", f"step_{s}.manifest.json"):
                try:
                    os.remove(os.path.join(self.checkpoint_dir, side))
                except OSError:
                    pass

    def saved_layout(self, step: int) -> dict | None:
        """The layout sidecar written alongside ``step_N``, or None
        (pre-round-5 checkpoints have none — callers must treat that as
        "same layout as mine", the old behavior). A present-but-corrupt
        sidecar raises ValueError: silently taking the same-layout restore
        path for (say) an async checkpoint would surface later as an
        opaque orbax shape mismatch pointing nowhere near the cause."""
        if not self.checkpoint_dir:
            return None
        path = os.path.join(self.checkpoint_dir, f"step_{step}.layout.json")
        try:
            with open(path) as f:
                raw = f.read()
        except OSError:
            return None  # missing sidecar: pre-round-5 checkpoint
        try:
            return json.loads(raw)
        except ValueError as exc:
            raise ValueError(
                f"corrupt checkpoint layout sidecar {path}: {exc}"
            ) from exc

    def restore_raw(self, step: int, abstract):
        """Restore ``step_N`` against an explicit abstract pytree (shapes/
        dtypes of the SOURCE layout) — the cross-topology path: the caller
        canonicalizes the result rather than assuming it matches its own
        state's shapes the way :meth:`prepare_or_restore` does."""
        if self._ckptr is None:
            raise RuntimeError("no checkpointer (orbax unavailable or no dir)")
        self.wait_pending()
        import jax

        path = os.path.join(self.checkpoint_dir, f"step_{step}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, abstract)
        return self._retry(
            lambda: self._ckptr.restore(path, abstract),
            f"restore step_{step}",
        )

    def prepare_or_restore(
        self, state: TrainState, *, verified_step: int | None = None
    ) -> tuple[TrainState, int]:
        """Restore-or-init: the analog of ``prepare_or_wait_for_session``.

        Returns (state, start_step). With no checkpoint present, the passed-in
        freshly-initialized state is returned — every process computed the
        identical init from the shared seed, which is how "non-chief waits for
        chief's init" degenerates on a deterministic SPMD system.

        Durability (round 6): candidate steps are tried newest-first; a
        step whose manifest fails file verification, whose orbax restore
        raises, or whose restored leaves mismatch their recorded CRCs is
        skipped (with a RuntimeWarning naming it) and the next-newest is
        tried — a corrupt or partially written latest checkpoint costs
        one epoch of progress, not the run. But when checkpoints EXIST
        and every one of them fails, that is a systemic failure (storage
        outage outliving the retry budget, format mismatch, a fallback
        landing on an incompatible older layout) and it RAISES — silently
        re-initializing at step 0 would discard the run's progress and
        bury the cause. ``verified_step`` marks a step whose files the
        caller already verified this session (trainers probe
        ``newest_restorable_step`` first), skipping the redundant disk
        re-read+CRC pass for it."""
        if self._ckptr is None:
            return state, 0
        self.wait_pending()
        import jax

        candidates = list(reversed(checkpoint_steps(self.checkpoint_dir)))
        for step in candidates:
            if (
                step != verified_step
                and resilience.verify_files(self.checkpoint_dir, step) is False
            ):
                warnings.warn(
                    f"checkpoint step_{step} fails manifest verification; "
                    "trying the previous step",
                    RuntimeWarning,
                    stacklevel=2,
                )
                continue
            path = os.path.join(self.checkpoint_dir, f"step_{step}")
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
            try:
                resilience.failpoints.fire("ckpt.restore")
                restored = self._retry(
                    lambda: self._ckptr.restore(path, abstract),
                    f"restore step_{step}",
                )
            except Exception as exc:  # noqa: BLE001 — fall back per contract
                warnings.warn(
                    f"checkpoint step_{step} failed to restore "
                    f"({type(exc).__name__}: {exc}); trying the previous step",
                    RuntimeWarning,
                    stacklevel=2,
                )
                continue
            try:
                manifest = resilience.load_manifest(self.checkpoint_dir, step)
            except ValueError:
                manifest = None
            if manifest is not None and not resilience.verify_leaves(
                restored, manifest
            ):
                warnings.warn(
                    f"checkpoint step_{step} restored with leaf CRC "
                    "mismatches; trying the previous step",
                    RuntimeWarning,
                    stacklevel=2,
                )
                continue
            if self._journal is not None:
                self._journal.emit(
                    "checkpoint_restore",
                    step=int(step),
                    fallback=step != candidates[0],
                )
            if self._metrics is not None:
                self._metrics.counter("checkpoint_restores_total").inc()
            return restored, step
        if candidates:
            raise RuntimeError(
                f"no restorable checkpoint in {self.checkpoint_dir}: all "
                f"{len(candidates)} candidate step(s) "
                f"({', '.join(f'step_{s}' for s in candidates)}) failed "
                "verification or restore — see the RuntimeWarnings above; "
                "refusing to silently re-initialize at step 0 over an "
                "existing run's progress"
            )
        return state, 0

    # -- orderly shutdown (reference sv.request_stop/sv.stop) -------------

    def request_stop(self) -> None:
        self._stop_requested = True

    @property
    def should_stop(self) -> bool:
        if self._stop_requested:
            return True
        if self._heartbeat is not None:
            if self._heartbeat.failed_count() > 0:
                self._stop_requested = True
            elif (
                self._stall_timeout_ms > 0
                and hasattr(self._heartbeat, "stalled_count")
                and self._heartbeat.stalled_count(self._stall_timeout_ms) > 0
            ):
                # Live-but-stalled worker (beating, progress frozen): same
                # exit as a dead one — stop at the boundary with the
                # checkpoints intact rather than hanging forever.
                self._stop_requested = True
        return self._stop_requested

    def stop(self) -> None:
        try:
            self.wait_pending()
        finally:
            if self._ckptr is not None:
                self._ckptr.wait_until_finished()
            self._stop_requested = True
