"""The training loop (component C14, SURVEY.md §2) — strategy-agnostic.

Reproduces the reference loop's contract (reference tfdist_between.py:86-111):
``epochs`` × ``num_train_examples // batch_size`` steps, one compiled train
step per batch, Step/Epoch/Batch/Cost/AvgTime logs every ``log_frequency``
batches, full-test-set accuracy + wall time per epoch, scalar summaries, and
a final-cost line.

TPU-first deltas from the reference loop:

- the step is fully compiled (jit/pjit/shard_map per strategy) — no
  per-batch Python→runtime graph feed;
- cost fetches are *lazy*: the returned device scalar is only synced on the
  host at log/summary cadence, so JAX's async dispatch keeps the device
  busy (the reference blocked on ``sess.run`` fetching cost every batch);
- summaries are buffered per epoch and flushed off the hot path.
"""

from __future__ import annotations

import time
from typing import Callable

import jax

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.observability import journal as obs_journal
from distributed_tensorflow_tpu.observability.metrics import MetricsRegistry
from distributed_tensorflow_tpu.observability.spans import SpanRecorder
from distributed_tensorflow_tpu.ops import losses as losses_lib
from distributed_tensorflow_tpu.ops import optim as optim_lib
from distributed_tensorflow_tpu.parallel.strategy import (
    AsyncDataParallel,
    SingleDevice,
    Strategy,
)
from distributed_tensorflow_tpu.train.supervisor import Supervisor
from distributed_tensorflow_tpu.utils.logging import StepLogger
from distributed_tensorflow_tpu.utils.summary import SummaryWriter, lifecycle_event


class Trainer:
    def __init__(
        self,
        model,
        datasets,
        config: TrainConfig | None = None,
        *,
        strategy: Strategy | None = None,
        loss_fn: Callable | None = None,
        optimizer=None,
        summary_writer: SummaryWriter | None = None,
        supervisor: "Supervisor | None" = None,
        is_chief: bool = True,
        print_fn=print,
        journal=None,
        metrics: MetricsRegistry | None = None,
    ):
        self.model = model
        self.datasets = datasets
        self.config = config or TrainConfig()
        self.strategy = strategy or SingleDevice()
        self.loss_fn = loss_fn or losses_lib.cross_entropy
        self.optimizer = optimizer or optim_lib.sgd(self.config.learning_rate)
        self.summary_writer = summary_writer
        self.is_chief = is_chief
        self.print_fn = print_fn
        # Telemetry (round 10, observability/): the journal defaults to the
        # process-wide one (a no-op NullJournal unless observability
        # .configure ran) — every structured line below is rendered FROM a
        # journal event, byte-identical to the pre-journal output.
        self.journal = journal if journal is not None else obs_journal.get_journal()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.spans = SpanRecorder(journal=self.journal)

        self.state = self.strategy.init_state(self.model, self.optimizer, self.config.seed)
        self.train_step = self.strategy.make_train_step(
            self.model, self.loss_fn, self.optimizer
        )
        self.eval_fn = self.strategy.make_eval_fn(self.model)
        self._exchange = None
        if isinstance(self.strategy, AsyncDataParallel) and self.strategy.avg_every:
            self._exchange = self.strategy.make_exchange_fn()
        # Global-batch policy (round 8): the effective global batch this
        # run consumes per optimizer step. Derived from the config
        # (reference convention: batch_size per worker × replicas), but a
        # restore across a WORLD-SIZE change adopts the checkpoint's
        # recorded global batch instead — the resized gang keeps the same
        # optimization trajectory (steps/epoch, effective batch), each
        # surviving replica's shard just grows. See _adopt_batch_policy.
        self.global_batch = self.config.batch_size * self.strategy.num_replicas
        # Completed-epoch counter, persisted in the layout sidecar: the
        # step counter alone cannot recover it once incarnations at
        # DIFFERENT world sizes mixed their per-batch increments (async
        # advances num_replicas per global batch), and the cross-world
        # permutation fast-forward needs the true epoch count.
        self.epochs_completed = 0

        # Supervisor duties (C13): restore-or-init against checkpoint_dir.
        self.supervisor = supervisor
        if self.supervisor is None and self.config.checkpoint_dir:
            self.supervisor = Supervisor(
                is_chief=is_chief,
                checkpoint_dir=self.config.checkpoint_dir,
                keep_last_n=self.config.keep_last_n,
                io_retries=self.config.checkpoint_retries,
                io_backoff=self.config.checkpoint_retry_backoff,
                async_checkpoint=self.config.async_checkpoint,
            )
        self.start_step = 0
        if self.supervisor is not None:
            self.supervisor.attach_observability(
                self.journal, self.metrics, self.spans
            )
            src = None
            # Newest step that is not known-corrupt (manifest-verified,
            # train/resilience.py) — a truncated/flipped latest checkpoint
            # must point the restore at the previous valid one, not at an
            # opaque orbax failure.
            step = self.supervisor.newest_restorable_step()
            if step is not None:
                src = self.supervisor.saved_layout(step)
            if src is not None and not self._layout_compatible(src):
                # Cross-topology restore (round 5, mirror of LMTrainer):
                # the checkpoint was written under a different strategy
                # layout (async's stacked copies, or a different replica
                # count) — restore in ITS shapes, fold to the canonical
                # dense form, re-stage into this strategy's layout.
                raw = self.supervisor.restore_raw(
                    step, self._abstract_for_layout(src)
                )
                self.state = self.strategy.from_canonical(
                    self._canonicalize_from(raw, src)
                )
                self.start_step = step
            else:
                # verified_step: the probe above already CRC-verified this
                # step's files — skip the redundant disk re-read.
                self.state, self.start_step = (
                    self.supervisor.prepare_or_restore(
                        self.state, verified_step=step
                    )
                )
            if src is not None:
                self._adopt_batch_policy(src)
            self._restore_src = src
            self.epochs_completed = self._epochs_from_restore(src)

        # Scanned-epoch fast path (config.scan_epoch): one dispatch per epoch.
        # config.scan_epoch=None resolves by backend: on an accelerator the
        # per-batch eager loop pays the device-link dispatch latency 550×
        # per epoch (the round-1 gap: the documented Trainer API ran at
        # 0.15× the reference on the tunneled chip while bench.py's scanned
        # path ran at 240×), so non-CPU backends default to the scanned path.
        self._scanned_fn = None
        self._indexed_fn = None
        self._scan_rng = None
        self._stage_cache: dict = {}
        scan_epoch = self.config.scan_epoch
        has_indexed = hasattr(self.strategy, "make_indexed_scanned_train_fn")
        if scan_epoch is None:
            scan_epoch = (
                jax.default_backend() != "cpu"
                and (has_indexed or hasattr(self.strategy, "make_scanned_train_fn"))
                and not (self.config.per_worker_epoch and not has_indexed)
                and not getattr(self.strategy, "explicit", False)
            )
        if scan_epoch:
            if not (has_indexed or hasattr(self.strategy, "make_scanned_train_fn")):
                raise ValueError(
                    f"scan_epoch unsupported for {type(self.strategy).__name__}"
                )
            if self.config.per_worker_epoch and not has_indexed:
                # The reference's epoch convention (each worker passes over
                # the full dataset, reference tfdist_between.py:87) needs the
                # indexed scan's wrap-around index stream.
                raise ValueError(
                    "per_worker_epoch scanning requires an indexed scan path"
                )
            # Indexed variant when available: train arrays stay device-
            # resident across epochs; only [steps, batch] int32 indices are
            # uploaded per epoch (train/scan.py).
            if hasattr(self.strategy, "make_indexed_scanned_train_fn"):
                self._indexed_fn = self.strategy.make_indexed_scanned_train_fn(
                    self.model, self.loss_fn, self.optimizer
                )
            else:
                self._scanned_fn = self.strategy.make_scanned_train_fn(
                    self.model, self.loss_fn, self.optimizer
                )
            import numpy as _np

            self._scan_rng = _np.random.default_rng(self.config.seed)
            if (
                self.start_step
                and getattr(self, "_world_changed", False)
                and not self.config.per_worker_epoch
            ):
                self._fast_forward_permutations(self._restore_src or {})

        self.last_cost: jax.Array | None = None
        self._epoch_costs = None  # per-step costs of the last scanned epoch
        self.history: list[dict] = []
        self._graph_written = False
        self._compiled_run_fns: dict = {}

        if self.config.log_placement and self.is_chief:
            from distributed_tensorflow_tpu.utils import placement

            placement.describe(self.state.params, print_fn=self.print_fn)

    # -- cross-topology restore (round 5; LMTrainer carries the LM-mode
    # analog — see its _state_{to,from}_canonical) ------------------------

    def _layout_compatible(self, src: dict) -> bool:
        """True when the saved state's SHAPES match this strategy's (the
        ordinary bitwise prepare_or_restore applies). All sync-family
        strategies share the canonical dense shapes; async matches only
        async at the same replica count. Compared on the sidecar's SHAPE
        keys only (supervisor.layout_shape): round-8 policy keys
        (world/global_batch) ride the same sidecar but must not force a
        same-layout resume onto the cross-topology path."""
        from distributed_tensorflow_tpu.train.supervisor import layout_shape

        mine = self.strategy.layout_meta()
        if mine["mode"] != "async":
            return src.get("mode") != "async"
        return layout_shape(src) == layout_shape(mine)

    def _layout_meta(self) -> dict:
        """The checkpoint layout sidecar: the strategy's shape topology
        plus the round-8 restore policy — the world size and effective
        global batch this run trained with, which a resized gang's
        restore preserves (_adopt_batch_policy)."""
        meta = dict(self.strategy.layout_meta())
        meta["world"] = int(self.strategy.num_replicas)
        meta["global_batch"] = int(self.global_batch)
        meta["epochs"] = int(self.epochs_completed)
        return meta

    def _epochs_from_restore(self, src: dict | None) -> int:
        """Completed epochs at the restored step. The round-8 sidecar
        records it exactly; older sidecars fall back to deriving it from
        the step counter — correct for a single-world history, but a
        counter spanning incarnations at different ASYNC replica counts
        mixes increments, which is precisely why the sidecar now carries
        the count."""
        if not self.start_step:
            return 0
        if src is not None and src.get("epochs") is not None:
            return int(src["epochs"])
        spe = self.datasets.train.num_examples // max(1, self.global_batch)
        incr = 1
        if src is not None and src.get("mode") == "async":
            incr = int(src.get("replicas", src.get("world", 1)))
        return self.start_step // max(1, spe * incr)

    def _adopt_batch_policy(self, src: dict) -> None:
        """Global-batch policy across an elastic resize (round 8,
        docs/resilience.md): the checkpoint records the run's effective
        global batch; a restore onto a DIFFERENT world size keeps it —
        same steps/epoch, same effective batch, same optimization
        trajectory — by growing each surviving replica's shard, rather
        than silently shrinking the global batch with the gang (which
        would change what the remaining epochs optimize). Asserted
        shardable; the reference's per-worker epoch convention ties batch
        to worker count by definition, so it refuses a world change
        loudly instead."""
        saved = src.get("global_batch")
        saved_world = src.get("world")
        self._world_changed = (
            saved_world is not None
            and int(saved_world) != self.strategy.num_replicas
        )
        if saved is None or int(saved) == self.global_batch:
            return
        saved = int(saved)
        n = self.strategy.num_replicas
        if self.config.per_worker_epoch:
            raise ValueError(
                f"checkpoint was written with global_batch={saved} "
                f"(world={saved_world}) but per_worker_epoch ties the "
                f"effective batch to the worker count (now {n}); the "
                "reference convention cannot preserve the global batch "
                "across a resize — resume with per_worker_epoch=False or "
                "restore onto the original world size"
            )
        if saved % n:
            raise ValueError(
                f"checkpoint global_batch={saved} does not shard over "
                f"{n} replicas; resume on a world size dividing it (or "
                "accept a new trajectory by clearing the sidecar)"
            )
        if self.is_chief:
            # Structured, greppable — the trainer-side half of the gang's
            # Resize: line (rendered from the journal event).
            lifecycle_event(
                "restore",
                print_fn=self.print_fn,
                journal=self.journal,
                global_batch=saved,
                from_world=saved_world,
                world=n,
                config_batch=self.config.batch_size,
                config_global=self.global_batch,
                per_replica=saved // n,
            )
        self.global_batch = saved

    def _fast_forward_permutations(self, src: dict) -> None:
        """Replay the host permutation stream up to the restored epoch so
        a resumed-after-resize run draws the batches the uninterrupted
        run would have (the classifier analog of LMTrainer's
        next_indices fast-forward; with the global batch preserved,
        steps/epoch — and therefore the step→epoch mapping — is
        world-invariant). Only runs on a cross-world restore: same-world
        resumes keep their round-5 pinned behavior unchanged. The epoch
        count comes from the sidecar (``_epochs_from_restore``) — the
        step counter alone cannot recover it across mixed-world async
        histories."""
        train = self.datasets.train
        spe = train.num_examples // self.global_batch
        need = spe * self.global_batch
        draws_per_epoch = max(1, -(-need // train.num_examples))
        for _ in range(self.epochs_completed * draws_per_epoch):
            self._scan_rng.permutation(train.num_examples)

    def _abstract_for_layout(self, src: dict):
        """ShapeDtypeStructs of a checkpoint written under layout ``src``
        (this model + optimizer)."""
        import jax.numpy as jnp

        from distributed_tensorflow_tpu.parallel.strategy import TrainState

        params = jax.eval_shape(lambda: self.model.init(self.config.seed))
        opt = jax.eval_shape(self.optimizer.init, params)
        if src.get("mode") == "async":
            n = int(src["replicas"])
            stack = lambda t: jax.tree.map(  # noqa: E731
                lambda s: jax.ShapeDtypeStruct((n,) + s.shape, s.dtype), t
            )
            return TrainState(
                stack(params), stack(opt), jax.ShapeDtypeStruct((n,), jnp.int32)
            )
        return TrainState(params, opt, jax.ShapeDtypeStruct((), jnp.int32))

    def _canonicalize_from(self, state, src: dict):
        """Source-layout state → the canonical dense form (async merges
        its copies at the mean — its own effective_params — and sums the
        per-chip step vector; sync layouts only need the step fold).
        Integer leaves (e.g. adam's int32 count) take replica 0's value
        instead of mean-then-cast — the float mean is only exact below
        2^24 (parallel/strategy.py::merge_replica_leaf)."""
        import jax.numpy as jnp

        from distributed_tensorflow_tpu.parallel.strategy import (
            TrainState,
            merge_replica_leaf,
        )

        step = jnp.asarray(jnp.sum(state.step), jnp.int32)
        if src.get("mode") == "async":
            merge = lambda t: jax.tree.map(merge_replica_leaf, t)  # noqa: E731
            return TrainState(merge(state.params), merge(state.opt_state), step)
        return TrainState(state.params, state.opt_state, step)

    # -- pieces -----------------------------------------------------------

    def _stage_cached(self, name: str, arr) -> jax.Array:
        """Device-resident staging cache: full train/test arrays are placed
        once (replicated on the mesh when the strategy defines a replicated
        sharding) and reused across epochs and run_compiled calls. Round 1
        re-shipped ~170 MB per epoch through the ~20-40 ms device link —
        on the tunneled chip that transfer dwarfed the epoch's compute."""
        # The cache value keeps the host array alive and identity-checked:
        # keying by id() alone would go stale if a freed array's id were
        # reused by a different dataset.
        hit = self._stage_cache.get(name)
        if hit is None or hit[0] is not arr:
            sharding = getattr(self.strategy, "replicated_sharding", None)
            staged = self._place_replicated(arr, sharding)
            self._stage_cache[name] = hit = (arr, staged)
        return hit[1]

    @staticmethod
    def _place_replicated(a, sharding) -> jax.Array:
        """Place host data ``a`` replicated under ``sharding``. Takes the
        host array directly — an eager ``asarray`` first would commit it to
        the local default device and force an extra round trip through the
        device link before re-placement. In a multi-process mesh a plain
        device_put is not globally addressable; every process holds the
        identical full array (deterministic loaders), so assembly goes
        through make_array_from_process_local_data."""
        if sharding is None:
            return jax.numpy.asarray(a)
        if jax.process_count() > 1:
            import numpy as _np

            a = _np.asarray(a)
            return jax.make_array_from_process_local_data(sharding, a, a.shape)
        return jax.device_put(a, sharding)

    def evaluate(self) -> float:
        test = self.datasets.test
        return float(
            self.eval_fn(
                self.state,
                self._stage_cached("test_x", test.images),
                self._stage_cached("test_y", test.labels),
            )
        )

    def run_epoch(self, epoch: int, logger: StepLogger) -> None:
        self._epoch_costs = None  # eager path: guard judges last_cost only
        if self._scanned_fn is not None or self._indexed_fn is not None:
            return self._run_epoch_scanned(epoch, logger)
        cfg = self.config
        train = self.datasets.train
        # Global batch: the reference gave each of N workers a batch of 100
        # (reference tfdist_between.py:19,91), so N replicas consume N×100 —
        # unless a resize-restore adopted the checkpoint's recorded value
        # (self.global_batch, _adopt_batch_policy).
        global_batch = self.global_batch
        if cfg.per_worker_epoch:
            # Reference convention: each worker passes over the full dataset
            # per epoch; next_batch wraps across the shuffled permutations.
            batch_count = train.num_examples // cfg.batch_size
        else:
            batch_count = train.num_examples // global_batch
        summaries: list[tuple[int, jax.Array]] = []
        step_before = self.strategy.global_step(self.state)
        logger.reset_window()
        t_epoch = time.time()
        if cfg.prefetch:
            from distributed_tensorflow_tpu.data.prefetch import prefetch_batches

            batches = prefetch_batches(
                train.next_batch,
                global_batch,
                batch_count,
                self.strategy.prepare_batch,
                depth=cfg.prefetch,
            )
        else:
            batches = (
                self.strategy.prepare_batch(*train.next_batch(global_batch))
                for _ in range(batch_count)
            )
        for i, (bx, by) in enumerate(batches):
            self.state, cost = self.train_step(self.state, bx, by)
            self.last_cost = cost
            if self._exchange is not None and (i + 1) % self.strategy.avg_every == 0:
                self.state = self._exchange(self.state)
            if self.summary_writer is not None and self.is_chief:
                summaries.append((i, cost))
            # Only sync the host when a log line is due (async dispatch).
            if logger.is_due(i + 1, batch_count):
                logger.maybe_log_step(
                    step=self.strategy.global_step(self.state),
                    epoch=epoch,
                    batch=i,
                    batch_count=batch_count,
                    cost=self.strategy.cost_scalar(cost),
                )
        self._observe_step_time(
            (time.time() - t_epoch) * 1000 / max(batch_count, 1)
        )
        if self.summary_writer is not None and self.is_chief:
            incr = self._step_incr(step_before, batch_count)
            for i, cost in summaries:
                self.summary_writer.add_scalar(
                    "cost", self.strategy.cost_scalar(cost), step_before + (i + 1) * incr
                )

    def _run_epoch_scanned(self, epoch: int, logger: StepLogger) -> None:
        """One compiled dispatch for the whole epoch (train/scan.py). Update
        semantics match the eager loop exactly; log lines are emitted at the
        reference cadence afterwards from the returned per-step costs.

        Preferred path: the indexed scan — train arrays device-resident via
        ``_stage_cached``, per-epoch upload is only the [steps, batch] int32
        permutation (same host-RNG draw ``stage_epoch`` makes, so the batch
        stream is unchanged). Fallback (strategies without the indexed fn):
        stage the shuffled epoch and ship it whole."""
        cfg = self.config
        train = self.datasets.train
        global_batch = self.global_batch
        if self._indexed_fn is not None:
            import numpy as _np

            xs = self._stage_cached("train_x", train.images)
            ys = self._stage_cached("train_y", train.labels)
            if cfg.per_worker_epoch:
                # Reference convention (tfdist_between.py:87): each worker
                # runs num_examples/batch_size steps per epoch, wrapping
                # across reshuffles — i.e. the batch stream is successive
                # permutations concatenated (DataSet.next_batch tail-carry).
                steps = train.num_examples // cfg.batch_size
            else:
                steps = train.num_examples // global_batch
            need = steps * global_batch
            chunks, total = [], 0
            while total < need:
                p = self._scan_rng.permutation(train.num_examples)
                chunks.append(p)
                total += p.size
            perm = _np.concatenate(chunks)[:need] if len(chunks) > 1 else chunks[0][:need]
            # Replicated like xs/ys: on a multi-process mesh the jitted
            # computation takes only globally-addressable inputs — and every
            # process draws the identical permutation (same seed-derived
            # _scan_rng stream), so replication is consistent.
            idxs = self._place_replicated(
                perm.reshape(steps, global_batch).astype(_np.int32),
                getattr(self.strategy, "replicated_sharding", None),
            )
            step_before = self.strategy.global_step(self.state)
            mark = self.spans.mark()
            t0 = time.time()
            self.state, costs = self._indexed_fn(self.state, xs, ys, idxs)
        else:
            from distributed_tensorflow_tpu.train.scan import stage_epoch

            xs_np, ys_np = stage_epoch(
                train.images, train.labels, global_batch, rng=self._scan_rng
            )
            sharding = self.strategy.stage_sharding
            xs = jax.device_put(xs_np, sharding) if sharding else jax.numpy.asarray(xs_np)
            ys = jax.device_put(ys_np, sharding) if sharding else jax.numpy.asarray(ys_np)
            step_before = self.strategy.global_step(self.state)
            mark = self.spans.mark()
            t0 = time.time()
            self.state, costs = self._scanned_fn(self.state, xs, ys)
        # dispatch_fetch = jax.device_get + the host span: the fetch IS the
        # execution barrier (CLAUDE.md timing trap), and the span records
        # the honest dispatch→D2H window.
        costs = self.spans.dispatch_fetch(
            "epoch_scan", costs, start=mark, epoch=int(epoch)
        )
        elapsed = time.time() - t0
        self.last_cost = costs[-1]
        self._epoch_costs = costs  # anomaly guard sees every step's cost
        batch_count = costs.shape[0]
        avg_ms = elapsed * 1000 / batch_count  # uniform: one dispatch ran them all
        self._observe_step_time(avg_ms)
        self._emit_step_logs(
            costs,
            epoch,
            step_before,
            avg_ms,
            logger,
            step_incr=self._step_incr(step_before, batch_count),
        )

    def run_compiled(
        self,
        epochs: int | None = None,
        *,
        epoch_offset: int = 0,
        finalize: bool = True,
    ) -> dict:
        """Trace-scoped entry for :meth:`_run_compiled` (the whole-run
        fast path — full contract on the implementation just below): one
        trace id per run, reusing run()'s when chunked dispatches arrive
        inside it."""
        from distributed_tensorflow_tpu.observability import tracing

        with tracing.trace(tracing.current_trace()):
            try:
                return self._run_compiled(
                    epochs, epoch_offset=epoch_offset, finalize=finalize
                )
            finally:
                if finalize and self.supervisor is not None:
                    self.supervisor.wait_pending()

    def _run_compiled(
        self,
        epochs: int | None = None,
        *,
        epoch_offset: int = 0,
        finalize: bool = True,
    ) -> dict:
        """Whole-run fast path (train/compiled_run.py): every epoch, shuffle,
        and test eval compiled into ONE dispatch. Observable surface matches
        ``run()`` — same log lines (uniform AvgTime, as in the scanned path),
        same summaries, same return dict — with per-epoch granularity
        reconstructed post-hoc from the returned ``[epochs, steps]`` costs
        and ``[epochs]`` accuracies. The epoch shuffle runs on-device
        (distributionally equivalent to the host shuffle; see the module
        docstring of train/compiled_run.py for the exact semantics).
        ``epoch_offset`` shifts the printed/recorded epoch numbers — the
        k-epochs-per-dispatch middle tier (``config.epochs_per_dispatch``)
        calls this once per chunk."""
        cfg = self.config
        epochs = cfg.epochs if epochs is None else epochs
        if not hasattr(self.strategy, "make_compiled_run_fn"):
            raise ValueError(
                f"compiled run unsupported for {type(self.strategy).__name__}"
            )
        train, test = self.datasets.train, self.datasets.test
        global_batch = self.global_batch
        # per_worker_epoch (reference convention, tfdist_between.py:87): each
        # worker runs num_examples/batch_size steps per epoch; the compiled
        # program wraps its index stream across fresh permutations.
        steps_per_epoch = (
            train.num_examples // cfg.batch_size if cfg.per_worker_epoch else None
        )
        use_pallas = cfg.engine == "pallas"
        if use_pallas:
            # Probe once per trainer: the check issues eager dispatches
            # (~20-40 ms each through the tunnel) that warm repeated calls
            # must not re-pay. Model/optimizer/loss are fixed at __init__.
            # (A previous flat elif chain made the SECOND pallas call fall
            # through to the unknown-engine raise — the already-checked
            # case must be a no-op, not an error.)
            if not getattr(self, "_pallas_checked", False):
                self._check_pallas_engine()
                self._pallas_checked = True
        elif cfg.engine != "xla":
            raise ValueError(f"unknown engine {cfg.engine!r} (xla|pallas)")
        # Cache per (engine, epochs, batch, steps): each make_*_run_fn call
        # builds a fresh jit closure, so without the cache a repeated
        # run_compiled — resume, epoch-at-a-time, benchmark warm runs —
        # would re-trace and recompile the whole program every call.
        key = (cfg.engine, epochs, global_batch, steps_per_epoch)
        run_fn = self._compiled_run_fns.get(key)
        if run_fn is None:
            if use_pallas:
                from distributed_tensorflow_tpu.ops.pallas_mlp import (
                    make_fused_compiled_run_fn,
                )

                run_fn = make_fused_compiled_run_fn(
                    batch_size=global_batch,
                    epochs=epochs,
                    in_dim=self.model.in_dim,
                    hidden_dim=self.model.hidden_dim,
                    out_dim=self.model.out_dim,
                    learning_rate=cfg.learning_rate,
                    steps_per_epoch=steps_per_epoch,
                )
            else:
                run_fn = self.strategy.make_compiled_run_fn(
                    self.model,
                    self.loss_fn,
                    self.optimizer,
                    batch_size=global_batch,
                    epochs=epochs,
                    steps_per_epoch=steps_per_epoch,
                )
            self._compiled_run_fns[key] = run_fn
        if self.summary_writer is not None and self.is_chief and not self._graph_written:
            self.write_graph()
            self._graph_written = True
        logger = StepLogger(
            freq=cfg.log_frequency, print_fn=self.print_fn,
            journal=self.journal,
        )
        # Stage replicated (per-step batches are random gathers, and in a
        # multi-process mesh the inputs must be globally addressable), cached
        # across calls: a repeated/resumed run re-dispatches without
        # re-shipping the train+test arrays through the device link.
        stage = self._stage_cached
        step_before = self.strategy.global_step(self.state)
        # Fold the global step into the shuffle key so a resumed or repeated
        # compiled run draws fresh epoch permutations instead of replaying
        # the first run's (the eager path's host RNG advances across runs).
        shuffle_key = jax.random.fold_in(jax.random.key(cfg.seed), step_before)
        t0 = time.time()
        staged_args = (
            stage("train_x", train.images),
            stage("train_y", train.labels),
            stage("test_x", test.images),
            stage("test_y", test.labels),
            shuffle_key,
        )
        mark = self.spans.mark()
        if use_pallas:
            from distributed_tensorflow_tpu.ops.pallas_mlp import (
                from_fused,
                to_fused,
            )
            from distributed_tensorflow_tpu.parallel.strategy import TrainState

            fused, metrics = run_fn(to_fused(self.state.params), *staged_args)
            n_steps = int(metrics["costs"].shape[0] * metrics["costs"].shape[1])
            self.state = TrainState(
                from_fused(fused), self.state.opt_state, self.state.step + n_steps
            )
        else:
            self.state, metrics = run_fn(self.state, *staged_args)
        # D2H fetches double as the execution barrier (CLAUDE.md timing
        # trap); dispatch_fetch also records the honest dispatch span.
        costs = self.spans.dispatch_fetch(
            "compiled_run", metrics["costs"], start=mark,
            epochs=int(epochs), engine=cfg.engine,
        )
        accs = jax.device_get(metrics["accuracy"])
        elapsed = time.time() - t0
        batch_count = costs.shape[1]
        if costs.size:
            self.last_cost = costs[-1, -1]
        avg_ms = elapsed * 1000 / max(epochs * batch_count, 1)
        self._observe_step_time(avg_ms)
        # Per-batch global-step advance (num_replicas under async, 1 under
        # sync) — derived from the counter over the whole dispatch.
        incr = self._step_incr(step_before, epochs * batch_count)
        accuracy = 0.0
        for epoch in range(epochs):
            self._emit_step_logs(
                costs[epoch],
                epoch_offset + epoch,
                step_before + epoch * batch_count * incr,
                avg_ms,
                logger,
                step_incr=incr,
            )
            if self.is_chief:
                accuracy = float(accs[epoch])
                logger.log_epoch(test_accuracy=accuracy)
                step_now = step_before + (epoch + 1) * batch_count * incr
                if self.summary_writer is not None:
                    self.summary_writer.add_scalar("accuracy", accuracy, step_now)
                self.history.append(
                    {
                        "epoch": epoch_offset + epoch + 1,
                        "accuracy": accuracy,
                        "step": step_now,
                    }
                )
        self.epochs_completed += epochs
        if self.supervisor is not None:
            import numpy as _np

            self.supervisor.report_progress(self.strategy.global_step(self.state))
            if cfg.max_rollbacks and costs.size and not _np.isfinite(costs).all():
                # A single compiled dispatch cannot roll back mid-program;
                # the anomaly guard's durability half still holds — never
                # commit a poisoned state over the last good checkpoint
                # (the per-epoch run() path does the full restore+retry).
                if self.is_chief:
                    lifecycle_event(
                        "rollback_compiled",
                        print_fn=self.print_fn,
                        journal=self.journal,
                    )
            else:
                self.supervisor.save(
                    self.state,
                    self.strategy.global_step(self.state),
                    layout=self._layout_meta(),
                )
        final_cost = float(costs[-1, -1]) if costs.size else float("nan")
        if finalize and self.is_chief:
            logger.log_final(cost=final_cost)
            if self.summary_writer is not None:
                self.summary_writer.flush()
            self.metrics.flush_to(self.journal, component="trainer")
            self.journal.flush()
        return {
            "accuracy": float(accs[-1]) if accs.size else 0.0,
            "final_cost": final_cost,
            "global_step": self.strategy.global_step(self.state),
        }

    def _run_chunked(self, epochs: int) -> dict:
        """The k-epochs-per-dispatch middle tier
        (``config.epochs_per_dispatch``): the whole-run compiled program
        dispatched a chunk at a time — per-epoch logs/eval/summaries come
        from each chunk's fetched history, a checkpoint lands after every
        dispatch, and ``should_stop`` is honored at chunk boundaries. The
        lifecycle surface of ``run()`` at near-``run_compiled`` throughput
        (docs/benchmarks/tpu_single.md, the ``single-k*`` rows)."""
        import math

        from distributed_tensorflow_tpu.train.resilience import AnomalyGuard

        k = self.config.epochs_per_dispatch
        guard = AnomalyGuard.from_config(self.config)
        res = {
            "accuracy": 0.0,
            "final_cost": float("nan"),
            "global_step": self.strategy.global_step(self.state),
        }
        done = 0
        while done < epochs:
            n = min(k, epochs - done)
            last = done + n >= epochs
            step_before = self.strategy.global_step(self.state)
            res = self.run_compiled(n, epoch_offset=done, finalize=last)
            if (
                guard is not None
                and not math.isfinite(res["final_cost"])
                and res["global_step"] > step_before
            ):
                # A chunk went NaN mid-dispatch: run_compiled already
                # skipped its save; this host boundary is where the
                # restore can run — roll back and retry the chunk
                # (NaN-only here: the spike baseline needs the per-epoch
                # history the per-epoch run() path keeps). The
                # global_step guard keeps an empty dispatch's nan
                # placeholder from reading as an anomaly. The poisoned
                # chunk's epochs never landed in a checkpoint — uncount
                # them (run_compiled counted before skipping its save).
                self.epochs_completed = max(0, self.epochs_completed - n)
                self._anomaly_rollback(guard, "nan", done)
                continue
            done += n
            if self.supervisor is not None and self.supervisor.should_stop:
                if not last and self.is_chief:
                    StepLogger(
                        freq=self.config.log_frequency,
                        print_fn=self.print_fn,
                        journal=self.journal,
                    ).log_final(cost=res["final_cost"])
                    if self.summary_writer is not None:
                        self.summary_writer.flush()
                break
        return res

    def _check_pallas_engine(self) -> None:
        """engine="pallas" runs the fused whole-epoch grid kernel, which
        hard-codes the reference workload's math (MLP sigmoid/softmax, naive
        CE, plain constant-lr SGD, single device). Anything else must use
        the generic XLA engine — raise rather than silently change math."""
        from distributed_tensorflow_tpu.models.mlp import MLP

        cfg = self.config
        problems = []
        if not isinstance(self.model, MLP):
            problems.append(f"model {type(self.model).__name__} (need MLP)")
        if not isinstance(self.strategy, SingleDevice):
            problems.append(
                f"strategy {type(self.strategy).__name__} (need SingleDevice; "
                "use ops.pallas_mlp.make_fused_async_epoch_fn for DP)"
            )
        if (
            cfg.optimizer != "sgd"
            or cfg.lr_schedule not in (None, "constant")  # optim.py treats both as constant
            or cfg.warmup_steps
        ):
            problems.append("optimizer config (need plain constant-lr sgd)")
        if cfg.loss != "naive":
            problems.append("loss config (need the reference's naive CE)")
        if cfg.accumulate_steps != 1 or cfg.grad_clip_norm:
            problems.append("accumulation/clipping (unsupported in the kernel)")
        # Semantic probes on top of the config strings: optimizer=/loss_fn=
        # can be passed to Trainer directly (build_trainer always does), so
        # the actual objects must also behave as plain sgd(lr) + naive CE.
        # Two applies expose momentum/adam/schedules/accumulation (all of
        # which match plain SGD on a single first step).
        import jax.numpy as jnp

        probe = jnp.asarray([[0.5, -1.5], [2.0, 0.25]])

        def two_updates(opt):
            s = opt.init(probe)
            u1, s = opt.update(probe, s, probe)
            u2, _ = opt.update(probe * 0.5, s, probe + u1)
            return jnp.concatenate([u1, u2])

        try:
            opt_ok = bool(
                jnp.allclose(
                    two_updates(self.optimizer),
                    two_updates(optim_lib.sgd(cfg.learning_rate)),
                )
            )
        except Exception:
            opt_ok = False
        if not opt_ok:
            problems.append(
                "optimizer (need plain constant-lr sgd semantics: no "
                "momentum/adam, schedule, warmup, clipping, or accumulation)"
            )
        y_probe = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        p_probe = jnp.asarray([[0.7, 0.3], [0.2, 0.8]])
        try:
            loss_ok = bool(
                jnp.allclose(
                    self.loss_fn(p_probe, y_probe),
                    losses_lib.cross_entropy(p_probe, y_probe),
                )
            )
        except Exception:
            loss_ok = False
        if not loss_ok:
            problems.append("loss (need the reference's naive CE)")
        if problems:
            raise ValueError(
                "engine='pallas' requires the reference workload shape; got "
                + "; ".join(problems)
            )

    def _observe_step_time(self, avg_ms: float) -> None:
        """Per-epoch average step time into the metrics registry (the
        trainer-side slice of the telemetry layer; edges span the µs
        Pallas steps through the ~100 ms tunnel dispatches)."""
        from distributed_tensorflow_tpu.observability.metrics import (
            TIME_MS_EDGES,
        )

        self.metrics.histogram("step_time_ms", edges=TIME_MS_EDGES).observe(
            float(avg_ms)
        )

    def _step_incr(self, step_before: int, batch_count: int) -> int:
        """Global-step advance per batch of the epoch just run — derived
        from the counter itself (num_replicas under async, 1 under sync)."""
        return (self.strategy.global_step(self.state) - step_before) // max(
            batch_count, 1
        )

    def _emit_step_logs(
        self,
        costs,
        epoch: int,
        step_offset: int,
        avg_ms: float,
        logger: StepLogger,
        step_incr: int = 1,
    ) -> None:
        """Post-hoc reference-cadence step lines + cost scalars from a
        compiled dispatch's returned per-step costs (shared by the scanned
        and whole-run fast paths). ``step_incr`` is the global-step advance
        per batch (num_replicas under async, 1 under sync)."""
        batch_count = len(costs)
        for i in range(batch_count):
            if logger.is_due(i + 1, batch_count):
                logger.log_step_line(
                    step=step_offset + (i + 1) * step_incr,
                    epoch=epoch,
                    batch=i,
                    batch_count=batch_count,
                    cost=float(costs[i]),
                    avg_ms=avg_ms,
                )
        if self.summary_writer is not None and self.is_chief:
            for i in range(batch_count):
                self.summary_writer.add_scalar(
                    "cost", float(costs[i]), step_offset + (i + 1) * step_incr
                )

    def write_graph(self) -> None:
        """Dump the train step's jaxpr as the TensorBoard graph — the
        reference passed its TF graph to the FileWriter (reference
        tfsingle.py:69, tfdist_between.py:83-84). Traced on a zeros batch so
        the training data stream is not advanced."""
        import numpy as np

        train = self.datasets.train
        global_batch = self.global_batch
        bx, by = self.strategy.prepare_batch(
            np.zeros((global_batch,) + train.images.shape[1:], np.float32),
            np.zeros((global_batch,) + train.labels.shape[1:], np.float32),
        )
        self.summary_writer.add_graph(self.train_step, self.state, bx, by)

    # -- resilience (round 6: train/resilience.py) ------------------------

    def _anomaly_rollback(self, guard, kind: str, epoch: int) -> None:
        """Restore the newest valid checkpoint after an anomalous epoch
        (NaN/inf or spike) and leave the host data stream where it is —
        the offending epoch's draws are consumed, never replayed, so the
        retry trains on the NEXT data window (the PaLM spike protocol:
        restore + skip the offending batches). With no checkpoint yet,
        the rollback target is the deterministic seed re-init. Raises
        AnomalyError once ``max_rollbacks`` is spent — training on a
        poisoned state must be loud, never silent."""
        from distributed_tensorflow_tpu.train.resilience import AnomalyError

        detected_step = self.strategy.global_step(self.state)
        if self.supervisor is None or guard.exhausted:
            raise AnomalyError(
                f"anomalous cost (kind={kind}) at epoch {epoch} step "
                f"{detected_step} with no rollback budget left "
                f"({guard.rollbacks}/{guard.max_rollbacks} used"
                + ("" if self.supervisor else "; no supervisor") + ")"
            )
        guard.rollbacks += 1
        self.metrics.counter("rollbacks_total").inc()
        fresh = self.strategy.init_state(
            self.model, self.optimizer, self.config.seed
        )
        self.state, restored_step = self.supervisor.prepare_or_restore(fresh)
        self.last_cost = None
        # Resync the completed-epoch counter with the state we restored to
        # (a fallback restore can land more than one epoch back).
        try:
            side = self.supervisor.saved_layout(restored_step)
        except ValueError:
            side = None
        if side is not None and side.get("epochs") is not None:
            self.epochs_completed = int(side["epochs"])
        if self.is_chief:
            # Structured, greppable — same key=value shape as Preemption:.
            # One lifecycle_event fans out to stdout + journal + tfevents.
            lifecycle_event(
                "rollback",
                print_fn=self.print_fn,
                journal=self.journal,
                writer=self.summary_writer,
                scalar=("rollback", float(restored_step), detected_step),
                anomaly=kind,
                epoch=epoch,
                detected_step=detected_step,
                restored_step=restored_step,
                rollback=guard.rollbacks,
                max_rollbacks=guard.max_rollbacks,
            )

    # -- the loop ---------------------------------------------------------

    def run(self, epochs: int | None = None) -> dict:
        """Public entry: the whole run under the preemption contract —
        SIGTERM/SIGINT requests a stop, the loop exits at the next epoch
        (or dispatch-chunk) boundary with a final save, and the process
        can exit 0 (train/resilience.py)."""
        from distributed_tensorflow_tpu.observability import tracing
        from distributed_tensorflow_tpu.train.resilience import preemption_guard

        # Ambient trace (round 12): every journal event of this run —
        # steps, epochs, checkpoint saves, spans, rollbacks — carries one
        # trace id, so obs_report can separate interleaved runs sharing a
        # journal. Reuses an enclosing trace (a resumed run staying in
        # its caller's scope) instead of splitting it.
        from distributed_tensorflow_tpu.train.resilience import arm_stall_dump

        arm_stall_dump()  # $DTF_STALL_DUMP (elastic launcher) or no-op
        with tracing.trace(tracing.current_trace()), preemption_guard(
            self.supervisor,
            enabled=self.config.handle_preemption,
            print_fn=self.print_fn,
            journal=self.journal,
        ):
            try:
                return self._run(epochs)
            finally:
                # Async-checkpoint drain (round 22): run() returns only
                # once every submitted save is durable on disk — callers
                # (and tests) probe checkpoints right after.
                if self.supervisor is not None:
                    self.supervisor.wait_pending()

    def _run(self, epochs: int | None = None) -> dict:
        cfg = self.config
        if cfg.compiled_run:
            return self.run_compiled(epochs)
        epochs = cfg.epochs if epochs is None else epochs
        if cfg.epochs_per_dispatch:
            return self._run_chunked(epochs)
        if self.summary_writer is not None and self.is_chief and not self._graph_written:
            # Once per trainer: TensorBoard expects at most one graph per run,
            # and run() may be called repeatedly (resume, epoch-at-a-time).
            self.write_graph()
            self._graph_written = True
        logger = StepLogger(
            freq=cfg.log_frequency, print_fn=self.print_fn,
            journal=self.journal,
        )
        from distributed_tensorflow_tpu.train.resilience import AnomalyGuard

        guard = AnomalyGuard.from_config(cfg)
        accuracy = 0.0
        epoch, profiled = 0, False
        while epoch < epochs:
            if epoch == 0 and cfg.profile_dir and not profiled:
                from distributed_tensorflow_tpu.utils import profiler

                profiled = True
                with profiler.trace(cfg.profile_dir):
                    self.run_epoch(epoch, logger)
            else:
                self.run_epoch(epoch, logger)
            if guard is not None:
                # Judge the epoch BEFORE eval/save: an anomalous state
                # must neither reach the checkpoint directory nor count
                # as a good epoch. Every process computes the identical
                # verdict (deterministic costs), so multi-process runs
                # branch together.
                cost = self.strategy.cost_scalar(self.last_cost)
                kind = guard.classify(cost, costs=self._epoch_costs)
                if kind is not None:
                    self._anomaly_rollback(guard, kind, epoch)
                    continue  # retry this epoch index on the next window
                guard.record(cost)
            self.epochs_completed += 1  # a good epoch: the sidecar's count
            self.metrics.counter("epochs_total").inc()
            # EVERY process runs the eval — it is a global-mesh computation
            # (sharded-param strategies gather over collectives), so a
            # chief-only dispatch would hang or die once non-chief
            # processes move on (the multi-host LM smoke caught exactly
            # this in lm_trainer.py); only the chief logs and records it.
            accuracy = self.evaluate()
            if self.is_chief:
                logger.log_epoch(test_accuracy=accuracy)
                if self.summary_writer is not None:
                    self.summary_writer.add_scalar(
                        "accuracy", accuracy, self.strategy.global_step(self.state)
                    )
                self.history.append(
                    {
                        "epoch": epoch + 1,
                        "accuracy": accuracy,
                        "step": self.strategy.global_step(self.state),
                    }
                )
            if self.supervisor is not None:
                # Epoch boundary = demonstrable progress: bump the heartbeat
                # progress counter BEFORE the save (the save itself can be
                # slow; the work it persists is already done), so the
                # elastic agent's stall clock resets on real forward motion.
                self.supervisor.report_progress(
                    self.strategy.global_step(self.state)
                )
                self.supervisor.save(
                    self.state,
                    self.strategy.global_step(self.state),
                    layout=self._layout_meta(),
                )
                if self.supervisor.should_stop:
                    break
            epoch += 1
        final_cost = (
            self.strategy.cost_scalar(self.last_cost)
            if self.last_cost is not None
            else float("nan")
        )
        if self.is_chief:
            logger.log_final(cost=final_cost)
            if self.summary_writer is not None:
                self.summary_writer.flush()
            self.metrics.flush_to(self.journal, component="trainer")
            self.journal.flush()
        return {
            "accuracy": accuracy,
            "final_cost": final_cost,
            "global_step": self.strategy.global_step(self.state),
        }
