"""Local-SGD / DiLoCo outer loop for the LM family — the paper's async
thesis at LM scale.

The reference's signature result is that ASYNC parameter-server training
beats sync at fixed wall-clock because workers apply updates the moment
they have them instead of waiting for the slowest peer (reference
tfdist_between.py:64-66, README.md:66-74; reproduced by our oracles:
async 0.8156 vs sync 0.618 @ 2 workers/100 epochs,
tools/parity_converged.py). ``make_lm_async_parts`` carries that claim to
the GPT family as per-chip copies exchanging at the mean. This module is
the *communication-reducing* modern form of the same thesis — local-SGD
with a DiLoCo-style outer optimizer (Douillard et al. 2023):

- each worker runs ``sync_every`` = H **inner** steps with the ordinary
  inner optimizer on its own data shard (zero cross-worker traffic);
- the gang then applies ONE **outer** update from the pseudo-gradient

      Δ = θ_start − mean_w(θ_w)

  through Nesterov momentum:  m ← μ·m + Δ;  θ ← θ_start − η_out·(Δ + μ·m)
  (``nesterov=False`` uses the heavy-ball form θ ← θ_start − η_out·m);
  every worker copy then jumps to the new θ, which becomes the next
  round's θ_start.

That is H× fewer all-reduce rounds per token than sync dp — and on the
tunneled v5e, where every dispatch carries a ~100 ms roundtrip, the outer
round is also the natural dispatch unit, so comm reduction and dispatch
amortization compound (the whole H-step round rides the scanned-epoch
``lax.scan`` machinery as part of one dispatch).

``outer_lr`` defaults to **N (the worker count)** — the same convention
as ``AsyncDataParallel``/``make_lm_async_parts``'s ``update_scale=N``
(parallel/strategy.py:451-470): the reference PS applied all N workers'
updates *sequentially* to one parameter set, moving it N× the mean
worker movement per exchange; Δ is exactly the mean worker movement, so
``outer_lr=N`` with the default ``outer_momentum=0`` reproduces the
sequential-apply semantics, while ``outer_lr=1`` is pure local-SGD
averaging. DiLoCo-paper settings are the explicit opt-in —
``outer_lr≈0.7-1.0, outer_momentum=0.9`` — used by the convergence
record (an N× step COMPOUNDED by momentum is sanctioned by neither
regime and measurably overshoots, hence the momentum-free default).

Degenerate anchor: at ``sync_every=1, outer_lr=1, outer_momentum=0`` the
outer update IS the per-step parameter mean — the computation is
implemented to reduce to exactly ``pmean(θ_w)`` in that corner (see
:func:`outer_update`), which makes it bitwise-identical to the async
exchange (``make_lm_async_parts`` with ``avg_every=1, update_scale=1``)
and — for SGD, which is linear in the gradient — equal to the sync
data-parallel step up to float reassociation (both pinned in
tests/test_local_sgd.py).

Two engines, one math:

- :func:`make_lm_diloco_parts` — the gang on a live mesh: ``shard_map``
  over the data axis, per-worker copies as [n, ...] stacked leaves (the
  ``make_lm_async_parts`` layout), outer state replicated.
- :func:`make_lm_diloco_vmapped` — the same gang as ONE single-device
  program (``jax.vmap`` over the worker axis). Mathematically the same
  update; runs on any jax, including degraded containers without the
  mesh APIs — the engine ``tools/diloco_bench.py`` uses for the CPU
  perplexity record, and the LMTrainer's ``dp_mode="diloco"`` fallback
  when no mesh is given (``TrainConfig.diloco_workers``).

Round 17 — streaming/compressed DiLoCo (all levers default-off; the
round-14 path above stays bitwise):

- **Compressed deltas** (``delta_dtype="int8"|"fp8"``): the outer
  pseudo-gradient is quantized per-TENSOR
  (``ops/quantized.quantize_tensor``) before it crosses the wire, with
  an error-feedback residual carried in :class:`DiLoCoState` — each
  round compresses ``Δ + residual`` and keeps ``(Δ + residual) − Δ̂``
  for the next one, so compression error is deferred, never lost
  (1-bit-SGD/EF-SGD lineage). One byte per element + one f32 scale per
  tensor ≈ another 4× comm reduction on top of H×
  (:func:`delta_payload_nbytes` is the accounting).
- **Overlapped exchange** (``overlap=True``): the delta computed at a
  boundary goes IN FLIGHT and the completed outer update applies one
  round late — workers never wait on the all-reduce, because the value
  being applied finished exchanging during the round that just ran. In
  a real gang the payload streams as layer-wise partitions spread over
  the H inner steps (:func:`streaming_schedule` is that comm plan); the
  engines realize the algorithm's math (the stale apply), which is
  identical whether the partitions land mid-round or all at the next
  boundary. The in-flight state rides :class:`DiLoCoState` — dense,
  world-invariant, resize-safe like θ_start/momentum. Semantics that
  made it CONVERGE (measured; :func:`outer_round_step` docstring): the
  pseudo-gradient is the mean round MOVEMENT (landing-mean based, not
  anchor based) and workers MERGE toward the stale-applied anchor
  (:data:`OVERLAP_MERGE`) instead of resetting; halve the outer
  momentum under overlap (the one-round delay compounds it — μ=0.9
  diverges, μ≈0.4-0.5 matches the non-overlapped row).
- **Stale-tolerant gang** (:class:`DeltaExchange` +
  ``TrainConfig.stale_limit``): the synchronous engines above exchange
  in-graph (every worker at the same boundary); the mailbox exchange
  moves the outer round to the HOST — each member posts its
  (compressed) delta to a shared directory at its own boundary and
  applies the outer update from whatever peers have posted, weighting a
  delta that is ``age`` rounds old by ``1/(1+age)``
  (:func:`staleness_weight`) and dropping anything older than
  ``stale_limit``. A throttled member therefore contributes stale
  deltas instead of stalling the gang — the PS async thesis, third
  incarnation (PS → DiLoCo → stale-tolerant DiLoCo). Member anchors may
  transiently differ (each applies its own arrival view — exactly the
  reference PS's async parameter drift); checkpoints/eval are
  per-member as in any async mode.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.train import failpoints, resilience


class DiLoCoState(NamedTuple):
    """The ``opt_state`` slot of a diloco-mode ``TrainState``.

    ``inner`` are the per-worker inner optimizer states ([n, ...] stacked
    leaves, sharded/vmapped over the worker axis — they persist ACROSS
    outer rounds, the DiLoCo recipe); ``theta`` is the outer anchor
    θ_start (dense parameter shapes, replicated) and ``momentum`` the
    outer Nesterov buffer (same shapes). ``theta``/``momentum`` are
    world-size-invariant, which is what lets an elastic resize carry the
    outer state across a world change (train/lm_trainer.py).

    Round 17: ``residual`` is the error-feedback residual of the
    compressed-delta lever (dense parameter shapes; ``None`` when
    ``delta_dtype`` is off) and ``inflight`` the overlapped exchange's
    in-flight state — a dict ``{"delta": Δ̂, "landing": L}`` of the
    pending outer pseudo-gradient and the mean point the worker copies
    landed on at the last boundary (both dense; ``None`` when
    ``overlap`` is off). All are world-size-invariant like
    θ_start/momentum, so a diloco→diloco elastic resize carries them
    VERBATIM; ``None`` fields are empty pytree nodes — with the levers
    off, the state's leaves (and therefore its checkpoints) are
    byte-identical to round 14."""

    inner: Any
    theta: Any
    momentum: Any
    residual: Any = None
    inflight: Any = None


def outer_update(
    theta,
    mean_params,
    momentum,
    *,
    outer_lr: float,
    outer_momentum: float,
    nesterov: bool = True,
):
    """One outer apply: ``(θ_start, mean_w(θ_w), m) → (θ', m')``.

    Pseudo-gradient Δ = θ_start − mean_params; m' = μ·m + Δ; the applied
    step is Δ + μ·m' (Nesterov) or m' (heavy-ball); θ' = θ_start −
    η_out·step. ``outer_lr``/``outer_momentum`` are trace-time Python
    floats: the ``outer_lr==1 and outer_momentum==0`` corner is
    specialized to ``θ' = mean_params`` — algebraically identical
    (θ − 1·(θ − mean) = mean) and, as floats, EXACTLY the parameter mean,
    which is what makes ``sync_every=1`` degenerate bitwise to the async
    per-step exchange (module docstring)."""
    mu = float(outer_momentum)
    eta = float(outer_lr)
    delta = jax.tree.map(lax.sub, theta, mean_params)
    if eta == 1.0 and mu == 0.0:
        return mean_params, delta
    return outer_apply(
        theta,
        delta,
        momentum,
        outer_lr=eta,
        outer_momentum=mu,
        nesterov=nesterov,
    )


def outer_apply(
    theta,
    delta,
    momentum,
    *,
    outer_lr: float,
    outer_momentum: float,
    nesterov: bool = True,
):
    """The outer optimizer on an explicit pseudo-gradient:
    ``(θ_start, Δ, m) → (θ', m')`` — the half of :func:`outer_update`
    below the Δ computation, factored out for the round-17 levers (a
    compressed Δ̂ or a one-round-stale in-flight Δ is applied through
    exactly the same Nesterov recurrence)."""
    mu = float(outer_momentum)
    eta = float(outer_lr)
    new_m = (
        jax.tree.map(lambda m, d: mu * m + d, momentum, delta)
        if mu != 0.0
        else delta
    )
    if nesterov:
        step = (
            jax.tree.map(lambda d, m: d + mu * m, delta, new_m)
            if mu != 0.0
            else delta
        )
    else:
        step = new_m
    new_theta = jax.tree.map(lambda t, s: t - eta * s, theta, step)
    return new_theta, new_m


def compress_delta(delta, residual, delta_dtype: str):
    """Error-feedback compression of the outer pseudo-gradient: quantize
    ``Δ + residual`` per-tensor (``ops/quantized.quantize_tensor`` —
    one symmetric f32 scale per tensor, the wire format) and carry the
    quantization error forward: ``residual' = (Δ + residual) − Δ̂``.
    Returns ``(Δ̂, residual')`` — what the gang applies, and what the
    next round re-injects. Elementwise and replicated-in/replicated-out,
    so it composes under both engines unchanged."""
    from distributed_tensorflow_tpu.ops.quantized import (
        dequantize_tensor,
        quantize_tensor,
    )

    corr = jax.tree.map(lax.add, delta, residual)

    def roundtrip(x):
        q, s = quantize_tensor(x, delta_dtype)
        return dequantize_tensor(q, s, x.dtype)

    dhat = jax.tree.map(roundtrip, corr)
    new_residual = jax.tree.map(lax.sub, corr, dhat)
    return dhat, new_residual


# Streaming-merge mixing factor: how far an overlapped boundary pulls
# each worker copy toward the stale-applied global anchor (0 = keep
# local, 1 = full reset — the streaming-DiLoCo merge knob). Measured at
# toy scale (8-epoch copy-corpus grid, docs/benchmarks/diloco.md):
# α=0.25 with outer momentum ≈0.4-0.5 matches or beats the
# non-overlapped row (ppl 7.07-7.20 vs 7.25), α=0.75 and full reset
# degrade sharply (9.6 / 17.7-213). Both engines read THIS constant so
# they cannot drift.
OVERLAP_MERGE = 0.25


def outer_round_step(
    theta,
    mean_params,
    momentum,
    residual,
    inflight,
    *,
    outer_lr: float,
    outer_momentum: float,
    nesterov: bool = True,
    delta_dtype: str | None = None,
    overlap: bool = False,
):
    """ONE outer round under the round-17 levers, shared verbatim by both
    engines (a divergence here would split their proven equality):
    ``(θ_start, mean_w(θ_w), m, r, f) → (θ', m', r', f')``.

    With both levers off this IS :func:`outer_update` (trace-time Python
    branch — the round-14 path stays bitwise, including the
    ``outer_lr=1, μ=0`` mean specialization). ``delta_dtype`` routes the
    pseudo-gradient through :func:`compress_delta` (EF residual);
    ``overlap`` applies the IN-FLIGHT delta from the previous boundary
    and stashes this round's (compressed) delta in its place — the first
    boundary applies a zero delta, so the outer trajectory trails one
    round behind, which is exactly the slack a real gang's all-reduce
    hides behind the next H inner steps.

    Overlap semantics (both measured into shape at toy scale —
    docs/benchmarks/diloco.md):

    - the pseudo-gradient is the gang's mean ROUND MOVEMENT,
      ``Δ_r = L_{r-1} − mean_w(θ_w)`` with ``L`` the mean point the
      copies LANDED on at the previous boundary (carried in
      ``inflight["landing"]``) — measuring against the outer anchor θ
      instead (the non-overlapped definition) injects an
      anchor-mismatch term once workers stop starting rounds AT θ;
    - the engines MERGE instead of reset: ``θ_w ← (1−α)·θ_w + α·θ'``
      with ``α`` = :data:`OVERLAP_MERGE` (the streaming-DiLoCo merge) —
      a full reset to the one-round-stale θ' discards every round's
      fresh progress until its delta lands and measurably oscillates
      (ppl 17.7–213 vs 7.2 across outer settings when probed); the
      merge keeps the local half and pulls the copies geometrically
      toward the common anchor (dispersion × (1−α) per round).
    ``L`` updates to the mean of the merged landing points,
    ``(1−α)·mean + α·θ'`` — at ``α=1`` the whole scheme degenerates to
    the anchor-based reset form."""
    if delta_dtype is None and not overlap:
        theta2, m2 = outer_update(
            theta,
            mean_params,
            momentum,
            outer_lr=outer_lr,
            outer_momentum=outer_momentum,
            nesterov=nesterov,
        )
        return theta2, m2, residual, inflight
    if overlap:
        delta = jax.tree.map(
            lax.sub, inflight["landing"], mean_params
        )
    else:
        delta = jax.tree.map(lax.sub, theta, mean_params)
    if delta_dtype is not None:
        delta, residual = compress_delta(delta, residual, delta_dtype)
    if overlap:
        theta2, m2 = outer_apply(
            theta,
            inflight["delta"],
            momentum,
            outer_lr=outer_lr,
            outer_momentum=outer_momentum,
            nesterov=nesterov,
        )
        a = OVERLAP_MERGE
        landing = jax.tree.map(
            lambda mp, t2: (1.0 - a) * mp + a * t2, mean_params, theta2
        )
        inflight = {"delta": delta, "landing": landing}
    else:
        theta2, m2 = outer_apply(
            theta,
            delta,
            momentum,
            outer_lr=outer_lr,
            outer_momentum=outer_momentum,
            nesterov=nesterov,
        )
    return theta2, m2, residual, inflight


def resolve_outer_lr(outer_lr: float | None, num_workers: int) -> float:
    """The ONE place the ``None → N`` default lives (the
    ``update_scale=N`` convention both async APIs share — module
    docstring); both engines and the trainer's comm accounting route
    through it so they cannot drift."""
    return float(num_workers) if outer_lr is None else float(outer_lr)


def sync_rounds_between(count0: int, count1: int, sync_every: int) -> int:
    """Outer rounds fired by steps ``count0 .. count1-1`` (global step
    counter semantics: step ``t`` fires the exchange iff
    ``(t+1) % sync_every == 0`` — the ``make_lm_async_parts`` cadence).
    Host-side mirror of the traced predicate, used by the trainer's
    per-epoch comm accounting (``comm_stats`` journal events)."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    return count1 // sync_every - count0 // sync_every


def params_nbytes(params) -> int:
    """Bytes of ONE dense parameter set — the payload of one outer
    all-reduce round (sync dp moves the same bytes per STEP as gradient
    traffic; the ratio is the H× headline). Works on concrete arrays and
    ShapeDtypeStructs alike."""
    return int(
        sum(
            x.size * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(params)
        )
    )


def delta_payload_nbytes(params, delta_dtype: str | None) -> int:
    """Bytes ONE outer delta actually puts on the wire: the dense payload
    (:func:`params_nbytes`) at ``delta_dtype=None``, else one byte per
    element plus one f32 scale per tensor (the per-tensor symmetric wire
    format of :func:`compress_delta`). Works on concrete arrays and
    ShapeDtypeStructs alike — the trainer's ``comm_stats`` accounting
    and the :class:`DeltaExchange` file payloads both measure THIS."""
    if delta_dtype is None:
        return params_nbytes(params)
    if delta_dtype not in ("int8", "fp8"):
        raise ValueError(
            f"delta_dtype must be None, 'int8', or 'fp8'; got "
            f"{delta_dtype!r}"
        )
    leaves = jax.tree.leaves(params)
    return int(sum(x.size for x in leaves) + 4 * len(leaves))


def staleness_weight(age: int, stale_limit: int) -> float:
    """Weight of a delta that is ``age`` outer rounds old: ``1/(1+age)``
    inside the tolerance window, 0.0 beyond it (and for negative ages —
    a peer cannot be fresher than the boundary consuming it; the
    exchange clamps ahead-of-round posts to age 0 before calling).
    ``stale_limit=0`` admits same-round deltas only."""
    if age < 0 or age > stale_limit:
        return 0.0
    return 1.0 / (1.0 + age)


def streaming_schedule(
    params, sync_every: int, partitions: int | None = None
) -> list[dict]:
    """The overlapped exchange's comm plan: the outer delta partitioned
    LAYER-WISE (leaf order, greedy byte-balanced into ``partitions``
    groups — default one per leaf, capped at H) with each partition's
    all-reduce issued at an inner-step offset spread across the next
    round. Returns ``[{"partition", "leaves", "nbytes", "issue_step"},
    ...]`` with ``issue_step`` in ``[0, sync_every)``.

    This is the SCHEDULE a multi-host deployment issues so the payload
    streams while compute runs; the engines' math is independent of it —
    every partition completes within the round, so applying the
    assembled delta at the next boundary (what :func:`outer_round_step`
    does) is value-identical to consuming partitions as they land."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    leaves = jax.tree.leaves(params)
    if not leaves:
        return []
    if partitions is None:
        partitions = min(len(leaves), sync_every)
    partitions = max(1, min(int(partitions), len(leaves)))
    sizes = [
        int(x.size * jnp.dtype(x.dtype).itemsize) for x in leaves
    ]
    # Greedy balance in leaf order: start a new partition when the
    # current one holds its fair share (layer-wise contiguity preserved —
    # a partition is a run of adjacent leaves, i.e. adjacent layers).
    total = sum(sizes)
    target = total / partitions
    groups: list[list[int]] = [[]]
    acc = 0
    for i, nb in enumerate(sizes):
        remaining_groups = partitions - (len(groups) - 1)
        remaining_leaves = len(sizes) - i
        if (
            groups[-1]
            and acc + nb / 2 >= target
            and remaining_groups > 1
            and remaining_leaves >= remaining_groups
        ):
            groups.append([])
            acc = 0
        groups[-1].append(i)
        acc += nb
    plan = []
    for k, idxs in enumerate(groups):
        plan.append(
            {
                "partition": k,
                "leaves": len(idxs),
                "nbytes": int(sum(sizes[i] for i in idxs)),
                # Spread issue points over the round: partition k fires
                # after inner step floor(k·H/P) of the next round.
                "issue_step": (k * sync_every) // len(groups),
            }
        )
    return plan


def _local_inner_step(model, optimizer, ragged: bool):
    """One worker's inner step — shared verbatim by both engines (a
    divergence here would silently split their proven equality)."""
    import optax

    def step(p, o, tokens, lens):
        loss_fn = (
            (lambda q: model.loss(q, tokens, lens))
            if ragged
            else (lambda q: model.loss(q, tokens))
        )
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = optimizer.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, o, loss

    return step


def make_lm_diloco_parts(
    model,
    optimizer,
    mesh,
    *,
    axis: str = "data",
    sync_every: int,
    outer_lr: float | None = None,
    outer_momentum: float = 0.0,
    nesterov: bool = True,
    ragged: bool = False,
    delta_dtype: str | None = None,
    overlap: bool = False,
):
    """DiLoCo building blocks on a live mesh (the LMTrainer's
    ``dp_mode="diloco"`` engine) — same contract as
    :func:`~models.gpt.make_lm_async_parts`: returns ``(init_state,
    mapped)`` where

    - ``init_state(params, opt_state) -> (stacked_params, DiLoCoState,
      count)`` — per-worker copies ([n, ...] leaves sharded over
      ``axis``), outer anchor θ_start = params and zero momentum
      (replicated), plus the step counter the exchange keys on;
    - ``mapped(stacked_params, dstate, tokens, lens, count) ->
      (stacked_params, dstate, loss)`` — NOT jitted (call it inside your
      own jit/scan); tokens [n·B, L] sharded on the batch dim; loss is
      the cross-worker mean of the local losses.

    The exchange is a ``lax.cond`` keyed on the replicated ``count`` (the
    all-reduce fires only on round boundaries — a ``where`` would void
    the traffic bound, same trap as the async exchange).
    ``delta_dtype``/``overlap`` are the round-17 levers (module
    docstring), realized in the shared :func:`outer_round_step`; their
    state (EF residual, in-flight delta) rides the replicated half of
    ``DiLoCoState`` and is absent (None) when the levers are off."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.models.gpt import _default_lens
    from distributed_tensorflow_tpu.ops.collectives import to_varying

    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    n = mesh.shape[axis]
    eta = resolve_outer_lr(outer_lr, n)
    step_fn = _local_inner_step(model, optimizer, ragged)

    def init_state(params, opt_state):
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
            (params, opt_state),
        )
        stacked = jax.device_put(stacked, NamedSharding(mesh, P(axis)))
        repl = NamedSharding(mesh, P())
        theta = jax.device_put(params, repl)
        m = jax.device_put(jax.tree.map(jnp.zeros_like, params), repl)
        zeros = lambda: jax.device_put(  # noqa: E731
            jax.tree.map(jnp.zeros_like, params), repl
        )
        return (
            stacked[0],
            DiLoCoState(
                stacked[1],
                theta,
                m,
                zeros() if delta_dtype is not None else None,
                # Round 0: nothing in flight, every copy lands on θ_0
                # (a COPY — an alias of theta would donate the same
                # buffer twice under the scanned path's donate_argnums).
                {"delta": zeros(), "landing": jax.tree.map(jnp.copy, theta)}
                if overlap
                else None,
            ),
            jnp.zeros((), jnp.int32),
        )

    def local(params, inner, theta, m, residual, inflight, tokens, lens,
              count):
        p = jax.tree.map(lambda x: x[0], params)
        o = jax.tree.map(lambda x: x[0], inner)
        p, o, loss = step_fn(p, o, tokens, lens if ragged else None)
        pvary = partial(to_varying, axis_name=(axis,))

        def exchange(args):
            p, theta, m, residual, inflight = args
            # pmean outputs are typed invariant — exactly right for the
            # outer state (replicated, like residual/inflight, which
            # stay invariant through the elementwise round step); the
            # worker copy is re-cast to varying so both cond branches
            # agree under check_vma (the make_lm_async_parts pattern).
            pbar = jax.tree.map(lambda x: lax.pmean(x, axis), p)
            theta2, m2, r2, f2 = outer_round_step(
                theta,
                pbar,
                m,
                residual,
                inflight,
                outer_lr=eta,
                outer_momentum=outer_momentum,
                nesterov=nesterov,
                delta_dtype=delta_dtype,
                overlap=overlap,
            )
            if overlap:
                # Streaming merge (module constant OVERLAP_MERGE): keep
                # the local half — a full reset to the one-round-stale
                # anchor discards this round's progress until its delta
                # lands (it measurably oscillates; outer_round_step
                # docstring).
                target = jax.tree.map(
                    lambda local, t2: (1.0 - OVERLAP_MERGE) * local
                    + OVERLAP_MERGE * pvary(t2),
                    p,
                    theta2,
                )
            else:
                target = jax.tree.map(pvary, theta2)
            return target, theta2, m2, r2, f2

        p, theta, m, residual, inflight = lax.cond(
            (count + 1) % sync_every == 0,
            exchange,
            lambda args: args,
            (p, theta, m, residual, inflight),
        )
        return (
            jax.tree.map(lambda x: x[None], p),
            jax.tree.map(lambda x: x[None], o),
            theta,
            m,
            residual,
            inflight,
            lax.pmean(loss, axis),
        )

    lens_spec = (P(axis),) if ragged else (P(),)
    inner_fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P(), P(), P(axis))
        + lens_spec
        + (P(),),
        out_specs=(P(axis), P(axis), P(), P(), P(), P(), P()),
    )

    def mapped(params, dstate, tokens, lens, count):
        if lens is None:
            lens = _default_lens(tokens, ragged)
        p, inner, theta, m, residual, inflight, loss = inner_fn(
            params, dstate.inner, dstate.theta, dstate.momentum,
            dstate.residual, dstate.inflight, tokens, lens, count,
        )
        return p, DiLoCoState(inner, theta, m, residual, inflight), loss

    return init_state, mapped


def make_lm_diloco_vmapped(
    model,
    optimizer,
    num_workers: int,
    *,
    sync_every: int,
    outer_lr: float | None = None,
    outer_momentum: float = 0.0,
    nesterov: bool = True,
    ragged: bool = False,
    delta_dtype: str | None = None,
    overlap: bool = False,
):
    """The same DiLoCo gang as ONE single-device program: worker copies
    are [n, ...] stacked leaves advanced by ``jax.vmap`` over the worker
    axis, the exchange is a mean over axis 0 — mathematically the mesh
    engine with the parallelism replaced by vectorization (reduction
    order may differ at float precision; the per-worker inner step is
    the SAME function, and the round-17 levers route through the SAME
    :func:`outer_round_step`). Contract identical to
    :func:`make_lm_diloco_parts` (tokens [n·B, L]; the first batch
    dimension is split n ways in worker order, matching the mesh
    engine's ``P(axis)`` batch sharding)."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    if num_workers < 1:
        raise ValueError(f"num_workers must be >= 1, got {num_workers}")
    n = num_workers
    eta = resolve_outer_lr(outer_lr, n)
    step_fn = _local_inner_step(model, optimizer, ragged)
    vstep = jax.vmap(step_fn)

    def init_state(params, opt_state):
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
            (params, opt_state),
        )
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)  # noqa: E731
        return (
            stacked[0],
            DiLoCoState(
                stacked[1],
                params,
                zeros(),
                zeros() if delta_dtype is not None else None,
                # Round 0: nothing in flight, every copy lands on θ_0
                # (a COPY — see the mesh engine's donation note).
                {"delta": zeros(), "landing": jax.tree.map(jnp.copy, params)}
                if overlap
                else None,
            ),
            jnp.zeros((), jnp.int32),
        )

    def mapped(params, dstate, tokens, lens, count):
        b, L = tokens.shape
        if b % n:
            raise ValueError(
                f"batch {b} must divide over {n} emulated workers"
            )
        toks = tokens.reshape(n, b // n, L)
        wl = (
            lens.reshape(n, b // n)
            if ragged
            else jnp.zeros((n, b // n), jnp.int32)
        )
        p, inner, losses = vstep(params, dstate.inner, toks, wl)
        theta, m = dstate.theta, dstate.momentum
        residual, inflight = dstate.residual, dstate.inflight

        def exchange(args):
            p, theta, m, residual, inflight = args
            pbar = jax.tree.map(lambda x: jnp.mean(x, axis=0), p)
            theta2, m2, r2, f2 = outer_round_step(
                theta,
                pbar,
                m,
                residual,
                inflight,
                outer_lr=eta,
                outer_momentum=outer_momentum,
                nesterov=nesterov,
                delta_dtype=delta_dtype,
                overlap=overlap,
            )
            if overlap:
                # Streaming merge — same arithmetic as the mesh engine
                # (trailing-dim broadcast against the [n, ...] stack).
                p2 = jax.tree.map(
                    lambda local, t2: (1.0 - OVERLAP_MERGE) * local
                    + OVERLAP_MERGE * t2,
                    p,
                    theta2,
                )
            else:
                p2 = jax.tree.map(
                    lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
                    theta2,
                )
            return p2, theta2, m2, r2, f2

        p, theta, m, residual, inflight = lax.cond(
            (count + 1) % sync_every == 0,
            exchange,
            lambda args: args,
            (p, theta, m, residual, inflight),
        )
        return (
            p,
            DiLoCoState(inner, theta, m, residual, inflight),
            jnp.mean(losses),
        )

    return init_state, mapped


# ---------------------------------------------------------------------------
# Stale-tolerant gang: the host-mailbox outer exchange (round 17).
#
# The in-graph engines above are SYNCHRONOUS gangs — every worker reaches
# the boundary together (a shard_map pmean, or one vmapped program). The
# mailbox moves the outer round to the host: each member posts its
# (compressed) delta to a shared directory at its own boundary and applies
# the outer update from whatever peers have posted, weighted by staleness
# (module docstring). Files commit atomically (tmp + os.replace — the
# serve_fleet mailbox discipline), so a reader never sees a torn payload
# and a member crash leaves nothing half-written. numpy-only numerics: the
# encode/decode pair mirrors ops/quantized's per-tensor semantics exactly
# (pinned in tests/test_local_sgd.py) so the wire format cannot drift from
# the in-graph compressed path.
# ---------------------------------------------------------------------------


def _np_encode_delta(leaves, delta_dtype):
    """Encode leaves for the wire via :func:`ops.quantized.quantize_tensor`
    (the SAME quantizer as the in-graph compressed path — bit-equal by
    construction, not by a parallel numpy implementation; XLA's fp8 cast
    double-rounds midpoints differently than a naive ml_dtypes cast, so
    a mirror would drift): → ``(stored_leaves, scales,
    dequantized_leaves)``. ``stored`` is what hits the disk (int8, or
    the fp8 payload viewed uint8 — npz-safe); ``dequantized`` is what
    every reader reconstructs, returned so the poster's EF residual sees
    the wire values."""
    import numpy as np

    if delta_dtype is None:
        leaves = [np.asarray(x, np.float32) for x in leaves]
        return leaves, None, leaves
    if delta_dtype not in ("int8", "fp8"):
        raise ValueError(
            f"delta_dtype must be None, 'int8', or 'fp8'; got "
            f"{delta_dtype!r}"
        )
    from distributed_tensorflow_tpu.ops.quantized import quantize_tensor

    stored, scales, deq = [], [], []
    for x in leaves:
        q, scale = quantize_tensor(jnp.asarray(x, jnp.float32), delta_dtype)
        q = np.asarray(jax.device_get(q))
        scale = float(scale)
        if delta_dtype == "fp8":
            stored.append(q.view(np.uint8))
        else:
            stored.append(q)
        deq.append(q.astype(np.float32) * scale)
        scales.append(scale)
    return stored, np.asarray(scales, np.float32), deq


def _np_decode_delta(stored, scales, delta_dtype):
    """Inverse of :func:`_np_encode_delta` on the read side."""
    import numpy as np

    if delta_dtype is None:
        return [np.asarray(x, np.float32) for x in stored]
    out = []
    for x, s in zip(stored, scales):
        if delta_dtype == "fp8":
            import ml_dtypes

            x = x.view(ml_dtypes.float8_e4m3fn)
        out.append(x.astype(np.float32) * float(s))
    return out


class DeltaExchange:
    """Filesystem outer-delta mailbox for a stale-tolerant DiLoCo gang.

    One instance per gang member (``rank`` of ``world``), all pointing at
    the same ``dirpath`` (any shared filesystem). Protocol per outer
    round boundary (LMTrainer drives it when constructed with
    ``delta_exchange=``):

    1. :meth:`post` — EF-compress (``delta_dtype``) and atomically
       publish this member's pseudo-gradient for round ``r`` as
       ``w<rank>_r<round>.npz``; returns the dequantized wire values
       (what peers will read — the caller's residual must see these).
    2. :meth:`weighted_delta` — assemble the round's outer
       pseudo-gradient: own delta at weight 1 plus every peer post NOT
       YET CONSUMED by this member and no more than ``stale_limit``
       rounds old, each weighted ``1/(1+age)`` (:func:`staleness_weight`;
       posts from rounds ahead of ours clamp to age 0). Each posted
       delta is applied AT MOST ONCE (per-peer consumed-round
       watermark): a delta is one round of MOVEMENT, and re-applying a
       stalled peer's last post at every subsequent boundary would
       over-apply it by its cumulative discounted weight (the async-PS
       contract is each update applied exactly once). Peers with
       nothing new in the window simply do not contribute — the round
       NEVER waits.

    Old own files past the staleness window are garbage-collected at
    each post (every member cleans only its own). Member anchors may
    transiently differ across the gang (each applies its own arrival
    view) — the async-PS drift semantics, see the module docstring. The
    consumed watermark is in-memory: a member restarted from a
    checkpoint may re-consume posts still inside the window (bounded by
    ``stale_limit`` rounds of peer movement — the same replay bound any
    restore has).

    Integrity (round 19): every post carries a CRC32C envelope (the
    round-6 checkpoint-manifest kernel) over the stored array bytes,
    verified on read. A committed-but-corrupt post (CRC mismatch, bad
    zip — the storage layer corrupting committed bytes; atomic replace
    already keeps torn *tmp* files invisible) is SKIPPED, never
    consumed into the mean: the watermark advances past it (a
    permanently bad file must not block that peer's later posts
    forever), a structured ``mailbox_corrupt`` journal event fires, and
    ``corrupt_posts`` counts it. Transient unreadability (OSError — a
    shared-fs hiccup, a racing GC) keeps the old contract: break
    without advancing, retry next boundary. Pre-round-19 posts without
    a ``crc`` entry verify as legacy (accepted unchecked). Stale
    ``.tmp`` orphans from writers killed mid-post are age-guard swept
    on construction and at each post's GC pass
    (:func:`resilience.sweep_tmp_orphans`)."""

    _CORRUPT = object()  # _load sentinel: committed-but-bad, skip + advance

    def __init__(
        self,
        dirpath: str,
        rank: int,
        world: int,
        *,
        stale_limit: int = 0,
        delta_dtype: str | None = None,
        journal=None,
        metrics=None,
        orphan_age_s: float = 60.0,
    ):
        import os

        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if not 0 <= rank < world:
            raise ValueError(f"rank must be in [0, {world}), got {rank}")
        if stale_limit < 0:
            raise ValueError(
                f"stale_limit must be >= 0, got {stale_limit}"
            )
        if delta_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"delta_dtype must be None, 'int8', or 'fp8'; got "
                f"{delta_dtype!r}"
            )
        self.dirpath = str(dirpath)
        self.rank = int(rank)
        self.world = int(world)
        self.stale_limit = int(stale_limit)
        self.delta_dtype = delta_dtype
        self.journal = journal  # LMTrainer wires its own; None → process
        self.metrics = metrics  # round 21: counters beside the journal
        self.orphan_age_s = float(orphan_age_s)
        self.corrupt_posts = 0  # committed-but-corrupt peer posts skipped
        # Per-peer consumed-round watermark: each posted delta is
        # applied at most once (class docstring).
        self._consumed: dict[int, int] = {}
        os.makedirs(self.dirpath, exist_ok=True)
        resilience.sweep_tmp_orphans(self.dirpath, age_s=self.orphan_age_s)

    def _emit_corrupt(self, *, file: str, reason: str, peer: int, round_idx: int):
        self.corrupt_posts += 1
        if self.metrics is not None:
            self.metrics.counter("mailbox_corrupt_posts_total").inc()
        j = self.journal
        if j is None:
            from distributed_tensorflow_tpu.observability import (
                journal as obs_journal,
            )

            j = obs_journal.get_journal()
        j.emit(
            "mailbox_corrupt",
            mailbox="delta",
            file=file,
            reason=reason,
            action="skipped",
            peer=int(peer),
            round=int(round_idx),
        )

    def _fname(self, rank: int, round_idx: int) -> str:
        return f"w{rank:04d}_r{round_idx:010d}.npz"

    def _scan(self) -> dict[int, list[int]]:
        """ONE directory scan → ``{rank: sorted rounds}``. gather() and
        the GC both read from this so a boundary costs O(1) listdir
        calls, not O(world) — on a shared filesystem each listdir is a
        metadata RPC and the boundary's wall_ms is journaled as the
        round's entire non-overlapped cost."""
        import os

        out: dict[int, list[int]] = {}
        for name in os.listdir(self.dirpath):
            if not (name.startswith("w") and name.endswith(".npz")):
                continue
            try:
                rank = int(name[1:5])
                r = int(name[7:-4])
            except ValueError:
                continue
            out.setdefault(rank, []).append(r)
        for rounds in out.values():
            rounds.sort()
        return out

    def _rounds_of(self, rank: int) -> list[int]:
        return self._scan().get(rank, [])

    def payload_nbytes(self, round_idx: int) -> int | None:
        """On-disk size of this member's posted payload for ``round_idx``
        (None before it posts) — the honest wire-bytes measurement the
        trainer's ``comm_stats`` accounting reports for the mailbox
        gang."""
        import os

        path = os.path.join(
            self.dirpath, self._fname(self.rank, round_idx)
        )
        try:
            return os.path.getsize(path)
        except OSError:
            return None

    @staticmethod
    def _payload_crc(stored, scales) -> int:
        """CRC32C envelope over the wire bytes: every stored array's
        buffer in index order, then the scales. Round-6 kernel
        (native fast path, table fallback — bit-identical)."""
        import numpy as np

        blob = b"".join(
            np.ascontiguousarray(x).tobytes() for x in stored
        )
        if scales is not None:
            blob += np.ascontiguousarray(scales).tobytes()
        return resilience._crc32c_bytes(blob)

    def post(self, round_idx: int, leaves) -> list:
        """Publish round ``round_idx``'s delta (numpy leaves, dense
        parameter order); returns the dequantized leaves exactly as
        peers will read them. Failpoints: ``delta.post`` at entry (+
        tear of the committed npz), ``delta.post.commit`` between the
        tmp write and the atomic replace."""
        import os

        import numpy as np

        failpoints.fire("delta.post")
        stored, scales, deq = _np_encode_delta(leaves, self.delta_dtype)
        payload = {f"a{i}": x for i, x in enumerate(stored)}
        payload["n"] = np.asarray(len(stored), np.int64)
        if scales is not None:
            payload["scales"] = scales
        payload["crc"] = np.asarray(
            self._payload_crc(stored, scales), np.int64
        )
        path = os.path.join(
            self.dirpath, self._fname(self.rank, round_idx)
        )
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        failpoints.fire("delta.post.commit")
        os.replace(tmp, path)  # commit is atomic: readers see all or nothing
        failpoints.tear("delta.post", path)
        # GC own history past the window (+1 so a peer mid-read of the
        # oldest admissible round never races its unlink).
        floor = round_idx - self.stale_limit - 1
        for r in self._rounds_of(self.rank):
            if r < floor:
                try:
                    os.remove(
                        os.path.join(self.dirpath, self._fname(self.rank, r))
                    )
                except OSError:
                    pass
        resilience.sweep_tmp_orphans(self.dirpath, age_s=self.orphan_age_s)
        return deq

    def _load(self, rank: int, round_idx: int):
        """Read + verify a peer post. Returns the decoded leaves, None
        for TRANSIENT unreadability (vanished to owner GC, an fs
        hiccup — retried next boundary, watermark unmoved), or
        ``_CORRUPT`` for a committed-but-bad file (CRC mismatch, torn
        zip structure, missing keys — skipped forever, watermark
        advances; class docstring)."""
        import os
        import zipfile

        import numpy as np

        path = os.path.join(self.dirpath, self._fname(rank, round_idx))
        try:
            failpoints.fire("delta.load")
            with np.load(path) as z:
                n = int(z["n"])
                stored = [z[f"a{i}"] for i in range(n)]
                scales = z["scales"] if "scales" in z.files else None
                crc = int(z["crc"]) if "crc" in z.files else None
        except OSError:
            return None  # vanished (owner GC) or transient fs hiccup
        except (KeyError, ValueError, zipfile.BadZipFile, EOFError):
            return self._CORRUPT  # committed file, broken structure
        if crc is not None and crc != self._payload_crc(stored, scales):
            return self._CORRUPT  # committed bytes flipped under the CRC
        return _np_decode_delta(stored, scales, self.delta_dtype)

    def gather(self, round_idx: int) -> list[tuple[int, int, float, list]]:
        """Peers' contributions for the boundary at ``round_idx``:
        ``[(rank, age, weight, leaves), ...]`` — every post this member
        has NOT yet consumed and still inside the staleness window, each
        weighted once (a peer that fell behind and catches up
        contributes each missed round's movement exactly once; posts
        ahead of our round clamp to age 0). Advances the per-peer
        consumed watermark — posts beyond the window are dropped forever
        (their movement is lost, the documented staleness cost), never
        retried. Own rank excluded (the caller holds its own fresh
        delta)."""
        posts = self._scan()
        out = []
        for rank in range(self.world):
            if rank == self.rank:
                continue
            floor = self._consumed.get(rank, -1)
            consumed = floor
            for r in posts.get(rank, []):
                if r <= floor:
                    continue
                if round_idx - r > self.stale_limit:
                    consumed = max(consumed, r)  # too old: dropped forever
                    continue
                leaves = self._load(rank, r)
                if leaves is None:
                    # Transiently unreadable (shared-fs hiccup) or
                    # vanished to owner GC: stop consuming THIS peer for
                    # the boundary without advancing the watermark — a
                    # hiccup retries next boundary (age+1, still
                    # weighted; consuming a newer post now would jump
                    # the watermark past the unread round forever), a
                    # GC'd file simply stops appearing in _scan.
                    break
                if leaves is self._CORRUPT:
                    # Committed-but-corrupt: skipped, NEVER consumed
                    # into the mean — but the watermark must advance
                    # past it, or a permanently bad file would block
                    # this peer's later posts forever.
                    consumed = max(consumed, r)
                    self._emit_corrupt(
                        file=self._fname(rank, r),
                        reason="crc",
                        peer=rank,
                        round_idx=r,
                    )
                    continue
                consumed = max(consumed, r)
                age = max(0, round_idx - r)  # ahead-of-round → fresh
                out.append(
                    (rank, age, staleness_weight(age, self.stale_limit),
                     leaves)
                )
            if consumed > floor:
                self._consumed[rank] = consumed
        return out

    def weighted_delta(self, round_idx: int, own_leaves):
        """The round's outer pseudo-gradient: staleness-weighted mean of
        own (weight 1) + every not-yet-consumed admissible peer post (a
        catching-up peer may contribute several entries, one per missed
        round). Returns ``(leaves, total_weight, contributors)`` with
        contributors ``[(rank, age, weight), ...]`` own-first — the
        trainer journals them, and ``total_weight`` (= 1 + Σ weights) is
        what the ``outer_lr=None`` default must scale by: the in-graph
        ``η=N`` convention compensates an exact 1/N mean over N
        contributing workers, so the mailbox's variable-contributor mean
        must scale by the ACTUAL total weight — scaling by the fixed
        world size would over-apply by up to N× whenever peers are
        missing or stale-dropped."""
        import numpy as np

        own = [np.asarray(x, np.float32) for x in own_leaves]
        peers = self.gather(round_idx)
        total = 1.0 + sum(w for _, _, w, _ in peers)
        acc = [x.copy() for x in own]
        for _, _, w, leaves in peers:
            for a, b in zip(acc, leaves):
                a += w * b
        mean = [a / total for a in acc]
        contributors = [(self.rank, 0, 1.0)] + [
            (r, age, w) for r, age, w, _ in peers
        ]
        return mean, total, contributors
