"""Local-SGD / DiLoCo outer loop for the LM family — the paper's async
thesis at LM scale.

The reference's signature result is that ASYNC parameter-server training
beats sync at fixed wall-clock because workers apply updates the moment
they have them instead of waiting for the slowest peer (reference
tfdist_between.py:64-66, README.md:66-74; reproduced by our oracles:
async 0.8156 vs sync 0.618 @ 2 workers/100 epochs,
tools/parity_converged.py). ``make_lm_async_parts`` carries that claim to
the GPT family as per-chip copies exchanging at the mean. This module is
the *communication-reducing* modern form of the same thesis — local-SGD
with a DiLoCo-style outer optimizer (Douillard et al. 2023):

- each worker runs ``sync_every`` = H **inner** steps with the ordinary
  inner optimizer on its own data shard (zero cross-worker traffic);
- the gang then applies ONE **outer** update from the pseudo-gradient

      Δ = θ_start − mean_w(θ_w)

  through Nesterov momentum:  m ← μ·m + Δ;  θ ← θ_start − η_out·(Δ + μ·m)
  (``nesterov=False`` uses the heavy-ball form θ ← θ_start − η_out·m);
  every worker copy then jumps to the new θ, which becomes the next
  round's θ_start.

That is H× fewer all-reduce rounds per token than sync dp — and on the
tunneled v5e, where every dispatch carries a ~100 ms roundtrip, the outer
round is also the natural dispatch unit, so comm reduction and dispatch
amortization compound (the whole H-step round rides the scanned-epoch
``lax.scan`` machinery as part of one dispatch).

``outer_lr`` defaults to **N (the worker count)** — the same convention
as ``AsyncDataParallel``/``make_lm_async_parts``'s ``update_scale=N``
(parallel/strategy.py:451-470): the reference PS applied all N workers'
updates *sequentially* to one parameter set, moving it N× the mean
worker movement per exchange; Δ is exactly the mean worker movement, so
``outer_lr=N`` with the default ``outer_momentum=0`` reproduces the
sequential-apply semantics, while ``outer_lr=1`` is pure local-SGD
averaging. DiLoCo-paper settings are the explicit opt-in —
``outer_lr≈0.7-1.0, outer_momentum=0.9`` — used by the convergence
record (an N× step COMPOUNDED by momentum is sanctioned by neither
regime and measurably overshoots, hence the momentum-free default).

Degenerate anchor: at ``sync_every=1, outer_lr=1, outer_momentum=0`` the
outer update IS the per-step parameter mean — the computation is
implemented to reduce to exactly ``pmean(θ_w)`` in that corner (see
:func:`outer_update`), which makes it bitwise-identical to the async
exchange (``make_lm_async_parts`` with ``avg_every=1, update_scale=1``)
and — for SGD, which is linear in the gradient — equal to the sync
data-parallel step up to float reassociation (both pinned in
tests/test_local_sgd.py).

Two engines, one math:

- :func:`make_lm_diloco_parts` — the gang on a live mesh: ``shard_map``
  over the data axis, per-worker copies as [n, ...] stacked leaves (the
  ``make_lm_async_parts`` layout), outer state replicated.
- :func:`make_lm_diloco_vmapped` — the same gang as ONE single-device
  program (``jax.vmap`` over the worker axis). Mathematically the same
  update; runs on any jax, including degraded containers without the
  mesh APIs — the engine ``tools/diloco_bench.py`` uses for the CPU
  perplexity record, and the LMTrainer's ``dp_mode="diloco"`` fallback
  when no mesh is given (``TrainConfig.diloco_workers``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class DiLoCoState(NamedTuple):
    """The ``opt_state`` slot of a diloco-mode ``TrainState``.

    ``inner`` are the per-worker inner optimizer states ([n, ...] stacked
    leaves, sharded/vmapped over the worker axis — they persist ACROSS
    outer rounds, the DiLoCo recipe); ``theta`` is the outer anchor
    θ_start (dense parameter shapes, replicated) and ``momentum`` the
    outer Nesterov buffer (same shapes). ``theta``/``momentum`` are
    world-size-invariant, which is what lets an elastic resize carry the
    outer state across a world change (train/lm_trainer.py)."""

    inner: Any
    theta: Any
    momentum: Any


def outer_update(
    theta,
    mean_params,
    momentum,
    *,
    outer_lr: float,
    outer_momentum: float,
    nesterov: bool = True,
):
    """One outer apply: ``(θ_start, mean_w(θ_w), m) → (θ', m')``.

    Pseudo-gradient Δ = θ_start − mean_params; m' = μ·m + Δ; the applied
    step is Δ + μ·m' (Nesterov) or m' (heavy-ball); θ' = θ_start −
    η_out·step. ``outer_lr``/``outer_momentum`` are trace-time Python
    floats: the ``outer_lr==1 and outer_momentum==0`` corner is
    specialized to ``θ' = mean_params`` — algebraically identical
    (θ − 1·(θ − mean) = mean) and, as floats, EXACTLY the parameter mean,
    which is what makes ``sync_every=1`` degenerate bitwise to the async
    per-step exchange (module docstring)."""
    mu = float(outer_momentum)
    eta = float(outer_lr)
    delta = jax.tree.map(lax.sub, theta, mean_params)
    new_m = (
        jax.tree.map(lambda m, d: mu * m + d, momentum, delta)
        if mu != 0.0
        else delta
    )
    if eta == 1.0 and mu == 0.0:
        return mean_params, new_m
    if nesterov:
        step = (
            jax.tree.map(lambda d, m: d + mu * m, delta, new_m)
            if mu != 0.0
            else delta
        )
    else:
        step = new_m
    new_theta = jax.tree.map(lambda t, s: t - eta * s, theta, step)
    return new_theta, new_m


def resolve_outer_lr(outer_lr: float | None, num_workers: int) -> float:
    """The ONE place the ``None → N`` default lives (the
    ``update_scale=N`` convention both async APIs share — module
    docstring); both engines and the trainer's comm accounting route
    through it so they cannot drift."""
    return float(num_workers) if outer_lr is None else float(outer_lr)


def sync_rounds_between(count0: int, count1: int, sync_every: int) -> int:
    """Outer rounds fired by steps ``count0 .. count1-1`` (global step
    counter semantics: step ``t`` fires the exchange iff
    ``(t+1) % sync_every == 0`` — the ``make_lm_async_parts`` cadence).
    Host-side mirror of the traced predicate, used by the trainer's
    per-epoch comm accounting (``comm_stats`` journal events)."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    return count1 // sync_every - count0 // sync_every


def params_nbytes(params) -> int:
    """Bytes of ONE dense parameter set — the payload of one outer
    all-reduce round (sync dp moves the same bytes per STEP as gradient
    traffic; the ratio is the H× headline). Works on concrete arrays and
    ShapeDtypeStructs alike."""
    return int(
        sum(
            x.size * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(params)
        )
    )


def _local_inner_step(model, optimizer, ragged: bool):
    """One worker's inner step — shared verbatim by both engines (a
    divergence here would silently split their proven equality)."""
    import optax

    def step(p, o, tokens, lens):
        loss_fn = (
            (lambda q: model.loss(q, tokens, lens))
            if ragged
            else (lambda q: model.loss(q, tokens))
        )
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = optimizer.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, o, loss

    return step


def make_lm_diloco_parts(
    model,
    optimizer,
    mesh,
    *,
    axis: str = "data",
    sync_every: int,
    outer_lr: float | None = None,
    outer_momentum: float = 0.0,
    nesterov: bool = True,
    ragged: bool = False,
):
    """DiLoCo building blocks on a live mesh (the LMTrainer's
    ``dp_mode="diloco"`` engine) — same contract as
    :func:`~models.gpt.make_lm_async_parts`: returns ``(init_state,
    mapped)`` where

    - ``init_state(params, opt_state) -> (stacked_params, DiLoCoState,
      count)`` — per-worker copies ([n, ...] leaves sharded over
      ``axis``), outer anchor θ_start = params and zero momentum
      (replicated), plus the step counter the exchange keys on;
    - ``mapped(stacked_params, dstate, tokens, lens, count) ->
      (stacked_params, dstate, loss)`` — NOT jitted (call it inside your
      own jit/scan); tokens [n·B, L] sharded on the batch dim; loss is
      the cross-worker mean of the local losses.

    The exchange is a ``lax.cond`` keyed on the replicated ``count`` (the
    all-reduce fires only on round boundaries — a ``where`` would void
    the traffic bound, same trap as the async exchange)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.models.gpt import _default_lens
    from distributed_tensorflow_tpu.ops.collectives import to_varying

    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    n = mesh.shape[axis]
    eta = resolve_outer_lr(outer_lr, n)
    step_fn = _local_inner_step(model, optimizer, ragged)

    def init_state(params, opt_state):
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
            (params, opt_state),
        )
        stacked = jax.device_put(stacked, NamedSharding(mesh, P(axis)))
        repl = NamedSharding(mesh, P())
        theta = jax.device_put(params, repl)
        m = jax.device_put(jax.tree.map(jnp.zeros_like, params), repl)
        return (
            stacked[0],
            DiLoCoState(stacked[1], theta, m),
            jnp.zeros((), jnp.int32),
        )

    def local(params, inner, theta, m, tokens, lens, count):
        p = jax.tree.map(lambda x: x[0], params)
        o = jax.tree.map(lambda x: x[0], inner)
        p, o, loss = step_fn(p, o, tokens, lens if ragged else None)
        pvary = partial(to_varying, axis_name=(axis,))

        def exchange(args):
            p, theta, m = args
            # pmean outputs are typed invariant — exactly right for the
            # outer state (replicated); the worker copy is re-cast to
            # varying so both cond branches agree under check_vma (the
            # make_lm_async_parts pattern).
            pbar = jax.tree.map(lambda x: lax.pmean(x, axis), p)
            theta2, m2 = outer_update(
                theta,
                pbar,
                m,
                outer_lr=eta,
                outer_momentum=outer_momentum,
                nesterov=nesterov,
            )
            return jax.tree.map(pvary, theta2), theta2, m2

        p, theta, m = lax.cond(
            (count + 1) % sync_every == 0,
            exchange,
            lambda args: args,
            (p, theta, m),
        )
        return (
            jax.tree.map(lambda x: x[None], p),
            jax.tree.map(lambda x: x[None], o),
            theta,
            m,
            lax.pmean(loss, axis),
        )

    lens_spec = (P(axis),) if ragged else (P(),)
    inner_fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P(axis)) + lens_spec + (P(),),
        out_specs=(P(axis), P(axis), P(), P(), P()),
    )

    def mapped(params, dstate, tokens, lens, count):
        if lens is None:
            lens = _default_lens(tokens, ragged)
        p, inner, theta, m, loss = inner_fn(
            params, dstate.inner, dstate.theta, dstate.momentum,
            tokens, lens, count,
        )
        return p, DiLoCoState(inner, theta, m), loss

    return init_state, mapped


def make_lm_diloco_vmapped(
    model,
    optimizer,
    num_workers: int,
    *,
    sync_every: int,
    outer_lr: float | None = None,
    outer_momentum: float = 0.0,
    nesterov: bool = True,
    ragged: bool = False,
):
    """The same DiLoCo gang as ONE single-device program: worker copies
    are [n, ...] stacked leaves advanced by ``jax.vmap`` over the worker
    axis, the exchange is a mean over axis 0 — mathematically the mesh
    engine with the parallelism replaced by vectorization (reduction
    order may differ at float precision; the per-worker inner step is
    the SAME function). Contract identical to
    :func:`make_lm_diloco_parts` (tokens [n·B, L]; the first batch
    dimension is split n ways in worker order, matching the mesh
    engine's ``P(axis)`` batch sharding)."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    if num_workers < 1:
        raise ValueError(f"num_workers must be >= 1, got {num_workers}")
    n = num_workers
    eta = resolve_outer_lr(outer_lr, n)
    step_fn = _local_inner_step(model, optimizer, ragged)
    vstep = jax.vmap(step_fn)

    def init_state(params, opt_state):
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
            (params, opt_state),
        )
        return (
            stacked[0],
            DiLoCoState(
                stacked[1], params, jax.tree.map(jnp.zeros_like, params)
            ),
            jnp.zeros((), jnp.int32),
        )

    def mapped(params, dstate, tokens, lens, count):
        b, L = tokens.shape
        if b % n:
            raise ValueError(
                f"batch {b} must divide over {n} emulated workers"
            )
        toks = tokens.reshape(n, b // n, L)
        wl = (
            lens.reshape(n, b // n)
            if ragged
            else jnp.zeros((n, b // n), jnp.int32)
        )
        p, inner, losses = vstep(params, dstate.inner, toks, wl)
        theta, m = dstate.theta, dstate.momentum

        def exchange(args):
            p, theta, m = args
            pbar = jax.tree.map(lambda x: jnp.mean(x, axis=0), p)
            theta2, m2 = outer_update(
                theta,
                pbar,
                m,
                outer_lr=eta,
                outer_momentum=outer_momentum,
                nesterov=nesterov,
            )
            p2 = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), theta2
            )
            return p2, theta2, m2

        p, theta, m = lax.cond(
            (count + 1) % sync_every == 0,
            exchange,
            lambda args: args,
            (p, theta, m),
        )
        return p, DiLoCoState(inner, theta, m), jnp.mean(losses)

    return init_state, mapped
