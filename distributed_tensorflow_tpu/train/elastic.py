"""Elastic gang-restart: supervised multi-host recovery (round 7).

The reference's only failure behavior was gRPC blocking forever (SURVEY.md
§5 "Failure detection"); round 6 upgraded that to *fail-stop* — durable
CRC-verified checkpoints, preemption exit, anomaly rollback, and a chief
that detects a dead worker and ends the job cleanly (docs/multihost.md).
This module closes the loop from fail-stop to **fail-recover**: production
TPU training treats worker death as routine (PaLM-style runs restart the
gang from the newest checkpoint automatically; TorchElastic-style agents
supervise each rank under a restart budget), and round 6's durable
checkpoints are exactly the substrate that makes automatic restart correct.

Topology
--------
One :class:`ElasticAgent` per gang member, held by a driver (an
:class:`ElasticGang`): the agent spawns its worker process and watches two
signals —

- the **exit code** (a non-zero or premature exit is a death), and
- the **heartbeat verdict** from an agent-hosted detector
  (:class:`HeartbeatHealth` over ``runtime/native.py``'s UDP coordinator):
  beats stopped past ``timeout_ms`` is *dead*; beats flowing but the
  payload's monotonic progress counter frozen past ``stall_timeout_ms`` is
  *live-but-stalled* — the failure mode an exit code can never show (a rank
  hung in a collective keeps its native sender thread beating forever, and
  before round 7 the job simply hung with it).

On any failure the gang is restarted as a unit: every member is killed
(checkpoint state is durable; the dead epoch is repaid, not lost), the
restart budget (``TrainConfig.max_restarts``) is charged, the gang waits an
exponentially backed-off, jittered delay (``resilience.retry`` — the same
state machine checkpoint I/O uses), and every member is relaunched. The
relaunched processes re-bootstrap ``jax.distributed`` under
``cluster.bounded_initialize`` (bounded timeout + retry, so members that
come up before their coordinator get retried attempts instead of an
indefinite hang) and resume from the newest VALID checkpoint via
``Supervisor.prepare_or_restore``. Each restart emits a structured
``Restart:`` line and a ``restart`` tfevents scalar; an exhausted budget
falls back to round 6's fail-stop (non-zero driver exit, checkpoints
intact).

The detector is hosted by the AGENT, out-of-band of the job
(``cluster.bootstrap(heartbeat_host=...)``: every task, chief included,
becomes a plain sender) — in-band detection cannot recover a stall, because
the chief is stuck in the same collective as the stalled rank.

``tools/launch_local.py --max-restarts N`` is this module's multi-process
driver (the reference's nohup-per-task workflow, now supervised);
``tests/test_elastic.py`` pins the state machine on a fake process table
and ``tests/integration/test_fault_injection.py`` proves the SIGKILL →
gang-restart → resume → rc 0 path end to end.

Shrink-to-fit resize (round 8)
------------------------------
Round 7 only ever relaunched at the ORIGINAL world size: a permanently
lost host meant an infinite restart loop until the budget burned out.
With ``min_workers < len(agents)`` the gang **resizes instead of merely
restarting**: after a failure verdict, each failed member's slot gets up
to ``rejoin_timeout_s`` for a replacement to register
(``ElasticAgent.available``); slots still vacant at the deadline are
BENCHED and the surviving members relaunch alone at the reduced world
size — down to the ``min_workers`` floor, below which the gang fail-stops
(round 6 semantics). Relaunched members get compact ranks ``0..M-1`` via
``topo_spawn_fn(rank, world, ranks)``; the workers re-bootstrap
``jax.distributed`` at the new ``num_processes``
(``launch.cluster_from_env`` reads the driver-set ``DTF_WORLD_SIZE`` /
``DTF_WORKER_RANKS``) and ``Supervisor.prepare_or_restore`` restores the
old-world checkpoint onto the new mesh through the round-5 canonical
layer. While degraded, every poll also probes the benched slots: a
replacement registering triggers a GROW — the same save→kill→relaunch→
cross-restore cycle back toward the original world. Every resize
(either direction) charges the restart budget once and emits a
structured ``Resize:`` line plus a ``world_size`` tfevents scalar; a
replacement that registers INSIDE the rejoin window keeps round 7's
fixed-size restart path bit-for-bit (identical spawns, no ``Resize:``
line). ``min_workers`` defaults to the full gang size, which disables
resizing entirely — the round-7 machine, unchanged.

Serving-fleet reuse (round 16)
------------------------------
``serve_fleet.py`` supervises N TextServer replicas with the SAME
primitives — one :class:`ElasticAgent` per replica (spawn/poll/kill),
:class:`HttpHealth` verdicts over each replica's ``/healthz``,
``resilience.backoff_delay`` for the jittered relaunch schedule, the
same restart budget + bench-below-floor discipline — but WITHOUT gang
semantics: serving replicas share no collectives, so one death never
poisons the others, and members fail and restart independently while
the fleet keeps serving (the paper's async-beats-sync thesis applied
to the serving tier; docs/serving.md §fleet).

Independent members (round 17)
------------------------------
``ElasticGang(independent=True)`` imports that serving-fleet discipline
back into TRAINING gangs whose members share no collectives — the
stale-tolerant DiLoCo mailbox gang (train/local_sgd.DeltaExchange):
members exchange outer deltas through a filesystem mailbox at their own
pace, so one member's death cannot wedge a peer in a collective. A
failure verdict therefore relaunches ONLY the failed members (the
survivors keep training; the relaunched member resumes from its
checkpoint and rejoins the mailbox at the current round, its first
contribution staleness-weighted like any late delta). The restart
budget is charged per relaunch batch and exhaustion fail-stops exactly
like the gang path; resizing (``min_workers < len(agents)``) does not
compose — an independent member that never comes back is simply a peer
that stops posting. Drain/straggler verdicts are off (a slow member
finishing after its peers is the POINT); health-based verdicts get a
``member_grace_s`` window after each relaunch so a restarting member's
silence is not immediately re-verdicted.
"""

from __future__ import annotations

import os
import signal as _signal
import time
from typing import Callable, Sequence

from distributed_tensorflow_tpu.observability import journal as obs_journal
from distributed_tensorflow_tpu.observability.metrics import MetricsRegistry
from distributed_tensorflow_tpu.train import failpoints, resilience
from distributed_tensorflow_tpu.utils.summary import lifecycle_event


class WorkerFailure(RuntimeError):
    """One or more gang members died or stalled. ``verdicts`` maps member
    name → verdict string (``rc=N``, ``dead``, ``stalled``, ``straggler``
    — still running past ``drain_timeout`` after a peer finished — or
    ``rejoined``: a benched member's replacement registered while the
    gang ran degraded, so the incarnation is retired to grow back)."""

    def __init__(self, verdicts: dict):
        self.verdicts = dict(verdicts)
        super().__init__(
            " ".join(f"{n}={v}" for n, v in sorted(self.verdicts.items()))
        )


class GangBelowFloor(WorkerFailure):
    """Resize planning left fewer than ``min_workers`` survivors: the gang
    fail-stops (round 6 semantics) instead of training on a mesh smaller
    than the operator said the job tolerates."""


class HeartbeatHealth:
    """Progress-aware health verdicts over the agent-hosted UDP detector.

    Owns a fresh ``HeartbeatCoordinator`` (one per gang incarnation — the
    gang recreates this each cycle so a relaunch never inherits the killed
    incarnation's stale last-seen clocks). ``classify(worker_id)`` returns:

    - ``"dead"`` — reported once then silent past ``timeout_ms``, or never
      reported and the grace window (default 5× timeout) has elapsed;
    - ``"stalled"`` — beating, but the payload's progress counter frozen
      past ``stall_timeout_ms`` (0 disables stall detection). Workers that
      never reported progress are not judged — startup import/compile must
      not read as a stall;
    - ``"ok"`` — otherwise.
    """

    def __init__(
        self,
        port: int,
        expected_workers: int,
        *,
        timeout_ms: int = 5000,
        stall_timeout_ms: int = 0,
        grace_ms: int | None = None,
        clock=time.monotonic,
    ):
        from distributed_tensorflow_tpu.runtime import native

        self._coord = native.HeartbeatCoordinator(
            port, expected_workers, timeout_ms=timeout_ms, grace_ms=grace_ms
        )
        self._timeout_ms = int(timeout_ms)
        self._stall_ms = int(stall_timeout_ms)
        self._grace_ms = int(grace_ms if grace_ms is not None else 5 * timeout_ms)
        self._clock = clock
        self._start = clock()

    def age_ms(self, worker_id: int) -> float:
        """Milliseconds since the member's last beat (-1: never seen) —
        the per-worker heartbeat-age gauge the gang exports (round 10)."""
        return float(self._coord.ms_since_seen(worker_id))

    def classify(self, worker_id: int) -> str:
        since = self._coord.ms_since_seen(worker_id)
        if since < 0:  # never reported
            elapsed_ms = (self._clock() - self._start) * 1000.0
            return "dead" if elapsed_ms > self._grace_ms else "ok"
        if since > self._timeout_ms:
            return "dead"
        if self._stall_ms > 0:
            since_progress = self._coord.ms_since_progress(worker_id)
            if since_progress > self._stall_ms:
                return "stalled"
        return "ok"

    def stop(self) -> None:
        self._coord.stop()


class HttpHealth:
    """:class:`HeartbeatHealth`'s verdicts over an HTTP ``/healthz``
    endpoint (observability/exporter.py) instead of the UDP detector —
    the probe the serving fleet router (serve_fleet.py) runs against its
    replicas, usable against any exporter-armed process.

    ``probe()`` fetches and parses the health document (returns None on
    any failure; the last good document stays at ``.last`` — it carries
    the ROUTING signals: ``queue_saturation``, ``slots_busy``,
    ``draining``). ``classify()`` mirrors the heartbeat verdicts:

    - ``"dead"`` — was reachable then unreachable past ``dead_after_s``,
      or never reachable and the startup ``grace_s`` elapsed (restore +
      first compile must not read as death);
    - ``"stalled"`` — reachable, but the payload's ``heartbeat_age_s``
      (time since the engine's last tick) exceeds ``stall_after_s``
      (0 disables) — the exporter thread answering while the engine loop
      is wedged, liveness without progress;
    - ``"ok"`` — otherwise.

    ``url`` may be a callable returning the URL (or None while unknown) —
    replicas that bind an ephemeral port publish it after startup, and an
    unknown URL counts as never-reachable. ``fetch``/``clock`` are
    injectable so the fast-tier router tests run without sockets."""

    def __init__(
        self,
        url,
        *,
        timeout_s: float = 2.0,
        dead_after_s: float = 5.0,
        grace_s: float = 60.0,
        stall_after_s: float = 0.0,
        fetch=None,
        clock=time.monotonic,
    ):
        self._url = url
        self._timeout_s = float(timeout_s)
        self._dead_after_s = float(dead_after_s)
        self._grace_s = float(grace_s)
        self._stall_after_s = float(stall_after_s)
        self._fetch = fetch if fetch is not None else self._http_fetch
        self._clock = clock
        self.last: dict | None = None
        self._last_ok: float | None = None
        self._start = clock()

    def _http_fetch(self, url: str) -> dict:
        import json as _json
        import urllib.request

        with urllib.request.urlopen(url, timeout=self._timeout_s) as resp:
            return _json.load(resp)

    def reset(self) -> None:
        """Fresh incarnation (a relaunched replica): forget the old
        endpoint's history and restart the never-reachable grace clock."""
        self.last = None
        self._last_ok = None
        self._start = self._clock()

    def probe(self) -> dict | None:
        url = self._url() if callable(self._url) else self._url
        if not url:
            return None
        try:
            # Failpoint inside the try: an injected raise IS a probe
            # failure — the classify() verdicts see exactly what a real
            # unreachable/hung endpoint produces.
            failpoints.fire("elastic.health")
            doc = self._fetch(url)
        except Exception:  # noqa: BLE001 — any probe failure is "no answer"
            return None
        if not isinstance(doc, dict):
            return None
        self.last = doc
        self._last_ok = self._clock()
        return doc

    def classify(self) -> str:
        doc = self.probe()
        now = self._clock()
        if doc is None:
            if self._last_ok is None:
                return "dead" if now - self._start > self._grace_s else "ok"
            return (
                "dead" if now - self._last_ok > self._dead_after_s else "ok"
            )
        if self._stall_after_s > 0:
            age = doc.get("heartbeat_age_s")
            if isinstance(age, (int, float)) and age > self._stall_after_s:
                return "stalled"
        return "ok"


class ElasticAgent:
    """Supervises ONE gang member: spawn, poll the exit code, kill.

    ``spawn_fn()`` returns a process handle exposing ``poll() -> rc|None``
    and ``kill()`` (``subprocess.Popen`` satisfies it; the fast-tier tests
    drive the whole machine with a fake process table). ``worker_id`` is
    the member's slot in the heartbeat detector.

    Resize hooks (round 8; both optional — absent, the agent is the
    round-7 fixed-slot member):

    - ``available_fn() -> bool`` — is this member's slot backed by a live
      host right now? Polled after a death (the rejoin window) and while
      the member sits benched (the grow trigger). ``None`` means always
      available — a dead member can always be relaunched in place, which
      is exactly round 7's fixed-size restart.
    - ``topo_spawn_fn(rank, world, ranks)`` — spawn this member at a
      NON-original topology: compact rank ``rank`` of ``world``, where
      ``ranks[r]`` is the original worker_id holding rank ``r`` (the
      driver exports it so workers can re-derive their cluster subset).
      Only consulted when the gang's current roster differs from the
      original; the original roster always spawns via ``spawn_fn()`` so a
      fully regrown gang is byte-identical to a fresh launch.

    Progress watchdog (round 22): ``heartbeat_fn() -> float | None``
    returns the seconds since this member's last progress beat (the
    launcher wires an mtime probe of ``<logdir>/worker<i>.heartbeat``),
    or None when the member has never beaten — startup/first-compile is
    not judged. The gang's stall verdict reads it through
    :meth:`heartbeat_age`."""

    def __init__(
        self,
        name: str,
        spawn_fn: Callable,
        *,
        worker_id: int | None = None,
        available_fn: Callable[[], bool] | None = None,
        topo_spawn_fn: Callable | None = None,
        heartbeat_fn: Callable[[], float | None] | None = None,
    ):
        self.name = name
        self.worker_id = worker_id
        self._spawn_fn = spawn_fn
        self.available_fn = available_fn
        self.topo_spawn_fn = topo_spawn_fn
        self.heartbeat_fn = heartbeat_fn
        self.handle = None

    def available(self) -> bool:
        """Is this member's slot backed by a live host? (See class doc.)"""
        return True if self.available_fn is None else bool(self.available_fn())

    def start(self, rank: int | None = None, world: int | None = None,
              ranks: tuple | None = None):
        failpoints.fire("elastic.relaunch")
        if rank is None:
            self.handle = self._spawn_fn()
        else:
            if self.topo_spawn_fn is None:
                raise RuntimeError(
                    f"{self.name}: gang resized to world={world} but this "
                    "agent has no topo_spawn_fn — pass one (or keep "
                    "min_workers at the full gang size to disable resizing)"
                )
            self.handle = self.topo_spawn_fn(rank, world, ranks)
        return self.handle

    def poll(self):
        """Exit code, or None (running / not yet started)."""
        return None if self.handle is None else self.handle.poll()

    def heartbeat_age(self) -> float | None:
        """Seconds since the member's last progress beat, or None (no
        ``heartbeat_fn`` wired, never beaten, or the probe failed —
        none of which is judgeable evidence of a stall)."""
        if self.heartbeat_fn is None:
            return None
        try:
            age = self.heartbeat_fn()
        except Exception:  # noqa: BLE001 — a broken probe is not a verdict
            return None
        return None if age is None else float(age)

    def request_dump(self) -> bool:
        """Best-effort SIGUSR1 to the member: its ``faulthandler`` dump
        (armed via ``resilience.arm_stall_dump`` / ``$DTF_STALL_DUMP``)
        lands all-thread stacks in the logdir. faulthandler's handler is
        C-level, so a rank wedged inside a collective CAN still dump; a
        SIGSTOPped one cannot (the signal queues until SIGCONT) — the
        stall verdict never waits on the dump."""
        pid = getattr(self.handle, "pid", None)
        usr1 = getattr(_signal, "SIGUSR1", None)
        if pid is None or usr1 is None:
            return False
        try:
            os.kill(pid, usr1)
            return True
        except OSError:
            return False

    def kill(self) -> None:
        """Hard-kill a live member (SIGKILL semantics — a rank hung in a
        collective ignores SIGTERM forever; its state is durable in the
        checkpoint, so the restart repays at most one epoch)."""
        if self.handle is None or self.handle.poll() is not None:
            return
        self.handle.kill()
        wait = getattr(self.handle, "wait", None)
        if wait is not None:  # reap, so the driver never accumulates zombies
            try:
                wait(timeout=30)
            except Exception:  # noqa: BLE001 — unkillable is the OS's problem
                pass


class ElasticGang:
    """The driver: N agents supervised as one gang under a restart budget.

    ``run()`` starts every member and polls until either every member has
    exited 0 (return 0) or a failure verdict lands — non-zero exit, dead,
    or stalled — at which point every live member is killed and the gang is
    relaunched after an exponentially backed-off, jittered delay, at most
    ``max_restarts`` times (``resilience.retry`` is the backoff state
    machine; ``max_restarts=0`` preserves round 6's fail-stop exactly:
    first failure → kill survivors → return 1). Each restart emits a
    structured ``Restart:`` line and, when a ``summary_writer`` is given, a
    ``restart`` tfevents scalar (value = restart ordinal).

    ``health_factory`` builds a fresh :class:`HeartbeatHealth` per gang
    incarnation (fresh detector state — a relaunch must not inherit the
    killed incarnation's silence); it may take one positional argument
    (the incarnation's world size) so a resized gang's detector expects
    the right member count. Once the first member exits 0, the rest
    must finish within ``drain_timeout`` seconds or the still-running
    members are verdicted ``straggler`` (a peer wedged in a collective the
    finished member will never rejoin beats forever — without the drain
    window the gang would hang with no verdict). ``sleep``/``clock``/
    ``poll_interval`` are injectable so the fast-tier tests run the whole
    machine without wall time or real processes.

    Resize (round 8): ``min_workers < len(agents)`` arms shrink-to-fit —
    see the module docstring for the full state machine. ``min_workers``
    defaults to the full gang size (resizing disabled: the round-7
    machine bit-for-bit). ``rejoin_timeout_s`` is how long a failed
    member's slot may stay vacant before the gang gives up on a
    replacement and relaunches without it; 0 decides immediately from
    one ``available()`` probe. The current roster is ``active`` (rank
    order); benched members are probed every poll and re-admitted — the
    grow path — by the same kill→relaunch→restore cycle. Every resize,
    either direction, charges one unit of the restart budget: a
    flapping host cannot spin the gang for free."""

    def __init__(
        self,
        agents: Sequence[ElasticAgent],
        *,
        max_restarts: int = 0,
        backoff: float = 1.0,
        max_backoff: float = 30.0,
        jitter: float = 0.25,
        health_factory: Callable[..., HeartbeatHealth] | None = None,
        poll_interval: float = 0.5,
        drain_timeout: float = 300.0,
        min_workers: int | None = None,
        rejoin_timeout_s: float = 0.0,
        independent: bool = False,
        member_grace_s: float = 60.0,
        stall_after_s: float = 0.0,
        print_fn=print,
        summary_writer=None,
        journal=None,
        metrics: MetricsRegistry | None = None,
        sleep=time.sleep,
        clock=time.monotonic,
        rng=None,
    ):
        self.agents = list(agents)
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.health_factory = health_factory
        self.poll_interval = float(poll_interval)
        self.drain_timeout = float(drain_timeout)
        self.min_workers = (
            len(self.agents) if min_workers is None else int(min_workers)
        )
        if not 1 <= self.min_workers <= len(self.agents):
            raise ValueError(
                f"min_workers must be in [1, {len(self.agents)}] "
                f"(= gang size), got {self.min_workers}"
            )
        self.rejoin_timeout_s = float(rejoin_timeout_s)
        if self.rejoin_timeout_s < 0:
            raise ValueError(
                f"rejoin_timeout_s must be >= 0, got {self.rejoin_timeout_s}"
            )
        self.independent = bool(independent)
        self.member_grace_s = float(member_grace_s)
        # Progress watchdog (round 22): a member whose process is ALIVE
        # but whose heartbeat file has not moved for stall_after_s gets a
        # "stalled" verdict — the SIGSTOP / wedged-collective class that
        # rc= polls and health probes can never see (mirror of the
        # round-21 breaker's frozen-replica reasoning). 0 disables. Size
        # it above the worst-case epoch + first-compile latency — the
        # never-beaten startup phase is not judged, but a long compile
        # BETWEEN beats is.
        self.stall_after_s = float(stall_after_s)
        if self.stall_after_s < 0:
            raise ValueError(
                f"stall_after_s must be >= 0, got {self.stall_after_s}"
            )
        if self.independent and self._elastic:
            raise ValueError(
                "independent=True does not compose with shrink-to-fit "
                "resizing (min_workers < gang size): independent members "
                "relaunch alone — a member that never comes back is a "
                "peer that stops posting, not a smaller mesh"
            )
        # clock() time until which each member's health verdicts are
        # suppressed (armed at its independent relaunch — a restarting
        # member's silence must not read as a fresh death).
        self._member_grace_until: dict[str, float] = {}
        self.print_fn = print_fn
        self.summary_writer = summary_writer
        # Telemetry (round 10): Restart:/Resize: lines become journal
        # events (rendered back byte-identically); the registry carries
        # restart/resize counters, the world-size gauge, and per-worker
        # heartbeat age. Defaults keep the round-7/8 surface untouched.
        self.journal = journal if journal is not None else obs_journal.get_journal()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.sleep = sleep
        self.clock = clock
        self.rng = rng
        self.restarts = 0  # restarts actually performed
        self.resizes = 0  # topology changes actually performed
        # Roster state: active members in rank order; benched members are
        # slots whose host did not come back inside the rejoin window.
        self.active: list[ElasticAgent] = list(self.agents)
        self.benched: list[ElasticAgent] = []

    @property
    def world_size(self) -> int:
        return len(self.active)

    @property
    def _elastic(self) -> bool:
        return self.min_workers < len(self.agents)

    # -- one gang incarnation --------------------------------------------

    def _make_health(self, world: int):
        """health_factory, passing the incarnation's world size when the
        factory takes a positional argument (round-7 zero-arg factories
        keep working unchanged)."""
        if self.health_factory is None:
            return None
        import inspect

        try:
            params = inspect.signature(self.health_factory).parameters.values()
            takes_world = any(
                p.kind
                in (
                    inspect.Parameter.POSITIONAL_ONLY,
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    inspect.Parameter.VAR_POSITIONAL,
                )
                for p in params
            )
        except (TypeError, ValueError):  # builtins without signatures
            takes_world = False
        return self.health_factory(world) if takes_world else self.health_factory()

    def _cycle(self) -> int:
        health = None
        first_done = None  # clock() when the first member exited 0
        # Identity roster (never resized, or fully regrown): the round-7
        # spawn path byte-for-byte — agents spawn via spawn_fn() with
        # their original worker_id as the detector slot. A resized roster
        # spawns with compact ranks 0..M-1 (topo_spawn_fn) and the
        # detector tracks those ranks (workers report worker_id =
        # task_index, which IS the compact rank after a resize).
        identity = self.active == self.agents
        ranks = tuple(
            a.worker_id if a.worker_id is not None else self.agents.index(a)
            for a in self.active
        )
        try:
            for rank, agent in enumerate(self.active):
                if identity:
                    agent.start()
                else:
                    agent.start(rank, len(self.active), ranks)
            health = self._make_health(len(self.active))
            while True:
                rcs = {a.name: a.poll() for a in self.active}
                verdicts = {
                    name: f"rc={rc}"
                    for name, rc in rcs.items()
                    if rc is not None and rc != 0
                }
                if health is not None:
                    for rank, a in enumerate(self.active):
                        wid = a.worker_id if identity else rank
                        if rcs[a.name] is None and wid is not None:
                            if hasattr(health, "age_ms"):
                                self.metrics.gauge(
                                    "heartbeat_age_ms",
                                    labels={"worker": a.name},
                                ).set(health.age_ms(wid))
                            if (
                                self.independent
                                and self._member_grace_until.get(a.name, 0)
                                > self.clock()
                            ):
                                continue  # relaunching: not judged yet
                            v = health.classify(wid)
                            if v != "ok":
                                verdicts[a.name] = v
                # Stall verdict (round 22): alive, past any rc/health
                # verdict, but the progress heartbeat is stale. Emit the
                # Stall: line, ask the member for its faulthandler dump
                # (best-effort), SIGKILL it, and hand the verdict to the
                # EXISTING recovery machinery (gang restart, shrink/
                # rejoin, or independent relaunch — nothing new below).
                if self.stall_after_s > 0:
                    for a in self.active:
                        if rcs[a.name] is not None or a.name in verdicts:
                            continue
                        age = a.heartbeat_age()
                        if age is not None and age > self.stall_after_s:
                            lifecycle_event(
                                "stall",
                                print_fn=self.print_fn,
                                journal=self.journal,
                                writer=self.summary_writer,
                                scalar=("stall", float(age), self.restarts),
                                member=a.name,
                                age_s=round(float(age), 3),
                                stall_after_s=self.stall_after_s,
                            )
                            self.metrics.counter("stalls_total").inc()
                            a.request_dump()
                            a.kill()
                            verdicts[a.name] = "stalled"
                if verdicts and self.independent:
                    # Independent members (module docstring): relaunch
                    # ONLY the failed members; survivors keep running.
                    # Budget exhaustion falls through to the gang-kill
                    # fail-stop below.
                    if self.restarts < self.max_restarts:
                        self._restart_members(verdicts)
                        continue
                    for a in self.agents:
                        a.kill()
                    raise WorkerFailure(verdicts)
                # Grow trigger: a benched slot's replacement registered
                # while the gang ran degraded. Retire the incarnation
                # (kill + relaunch at the bigger world) — unless someone
                # already finished cleanly, in which case the gang is
                # draining and growing would restart a completed job.
                if (
                    not verdicts
                    and self.benched
                    and not any(rc == 0 for rc in rcs.values())
                ):
                    back = [a for a in self.benched if a.available()]
                    if back:
                        verdicts = {a.name: "rejoined" for a in back}
                # Premature-exit guard: once any member finishes (rc 0),
                # the rest must drain within drain_timeout — a peer blocked
                # in a collective the finished member will never rejoin
                # would otherwise beat forever ("ok" to health) and hang
                # the gang with no verdict at all. Staggered-but-honest
                # completion finishes well inside the window. OFF for
                # independent members: they share no collectives, and a
                # slow member finishing long after its peers is exactly
                # the staleness the mailbox gang tolerates.
                if (
                    not verdicts
                    and not self.independent
                    and any(rc == 0 for rc in rcs.values())
                ):
                    if first_done is None:
                        first_done = self.clock()
                    elif self.clock() - first_done > self.drain_timeout:
                        verdicts = {
                            name: "straggler"
                            for name, rc in rcs.items()
                            if rc is None
                        }
                if verdicts:
                    # Gang semantics: one bad member poisons the incarnation
                    # (its peers are blocked in collectives it will never
                    # join) — kill every survivor and hand the verdicts up.
                    for a in self.agents:
                        a.kill()
                    raise WorkerFailure(verdicts)
                if all(rc == 0 for rc in rcs.values()):
                    return 0
                self.sleep(self.poll_interval)
        except WorkerFailure:
            raise
        except BaseException:
            # Not a gang verdict: spawn/detector failure (e.g. the
            # heartbeat port got grabbed between incarnations) or a driver
            # bug. The already-started members must not outlive the driver
            # as orphans holding the checkpoint dir.
            for agent in self.agents:
                agent.kill()
            raise
        finally:
            if health is not None:
                health.stop()

    def _restart_members(self, verdicts: dict) -> None:
        """Independent-mode relaunch: kill + backoff + respawn ONLY the
        verdicted members (one restart charged for the batch); arms each
        member's health grace window. Callers have already checked the
        budget."""
        self.restarts += 1
        self.metrics.counter("restarts_total").inc()
        delay = resilience.backoff_delay(
            self.restarts - 1,
            backoff=self.backoff,
            max_backoff=self.max_backoff,
            jitter=self.jitter,
            rng=self.rng,
        )
        lifecycle_event(
            "restart",
            print_fn=self.print_fn,
            journal=self.journal,
            writer=self.summary_writer,
            scalar=("restart", float(self.restarts), self.restarts),
            restart=self.restarts,
            max_restarts=self.max_restarts,
            cause=str(WorkerFailure(verdicts)),
            backoff_s=float(delay),
            independent=True,
            members=sorted(verdicts),
        )
        failed = [a for a in self.active if a.name in verdicts]
        for a in failed:
            a.kill()
        self.sleep(delay)
        for a in failed:
            a.start()
            self._member_grace_until[a.name] = (
                self.clock() + self.member_grace_s
            )

    def _plan_topology(self, exc: WorkerFailure) -> None:
        """Recompute the roster after a failure verdict (no-op unless
        ``min_workers < len(agents)``): give each failed member's slot up
        to ``rejoin_timeout_s`` to come back (``available()``), bench the
        slots that did not, re-admit benched slots that did — then either
        raise :class:`GangBelowFloor` (fewer than ``min_workers`` left) or
        record the resize with a structured ``Resize:`` line and a
        ``world_size`` tfevents scalar. Rosters rebuild in ORIGINAL agent
        order, so a fully regrown gang restores the original ranks (and
        spawns via the original, pre-resize path)."""
        if not self._elastic:
            return
        prev = list(self.active)
        failed = [
            a
            for a in self.active
            if exc.verdicts.get(a.name) not in (None, "rejoined")
        ]
        # Rejoin window: poll the failed slots until each has a
        # replacement or the budget runs out. available_fn=None (always
        # available) resolves instantly — the fixed-size restart.
        missing = [a for a in failed if not a.available()]
        if missing and self.rejoin_timeout_s > 0:
            deadline = self.clock() + self.rejoin_timeout_s
            wait = min(self.poll_interval, self.rejoin_timeout_s) or (
                self.rejoin_timeout_s
            )
            while missing and self.clock() < deadline:
                self.sleep(wait)
                missing = [a for a in missing if not a.available()]
        bench = set(missing)
        roster = []
        for a in self.agents:  # original order: a regrow restores ranks
            if a in bench:
                continue
            if a in self.benched and not a.available():
                continue
            roster.append(a)
        if roster == prev:
            return  # replacement(s) arrived in time: fixed-size restart
        if len(roster) < self.min_workers:
            floor = GangBelowFloor(exc.verdicts)
            floor.world = len(roster)
            raise floor
        dropped = [a.name for a in prev if a not in roster]
        rejoined = [a.name for a in roster if a not in prev]
        self.active = roster
        self.benched = [a for a in self.agents if a not in roster]
        self.resizes += 1
        self.metrics.counter("resizes_total").inc()
        self.metrics.gauge("world_size").set(len(roster))
        direction = (
            "shrink"
            if len(roster) < len(prev)
            else ("grow" if len(roster) > len(prev) else "swap")
        )
        # Structured, greppable — same key=value shape as Restart:. One
        # lifecycle_event fans out: stdout line + journal event + the
        # world_size tfevents scalar (utils/summary.py, round 10).
        lifecycle_event(
            "resize",
            print_fn=self.print_fn,
            journal=self.journal,
            writer=self.summary_writer,
            scalar=("world_size", float(len(roster)), self.restarts),
            world=len(roster),
            from_world=len(prev),
            min_workers=self.min_workers,
            direction=direction,
            dropped=dropped,
            rejoined=rejoined,
            restart=self.restarts,
            max_restarts=self.max_restarts,
        )

    def _on_retry(self, exc: WorkerFailure, attempt: int, delay: float) -> None:
        self.restarts = attempt + 1
        self.metrics.counter("restarts_total").inc()
        # Structured, greppable — same key=value shape as Preemption:/
        # Rollback:; the lifecycle_event fans out stdout + journal +
        # the restart tfevents scalar.
        lifecycle_event(
            "restart",
            print_fn=self.print_fn,
            journal=self.journal,
            writer=self.summary_writer,
            scalar=("restart", float(self.restarts), self.restarts),
            restart=self.restarts,
            max_restarts=self.max_restarts,
            cause=str(exc),
            backoff_s=float(delay),
        )
        # After the Restart bookkeeping: decide WHAT relaunches (may wait
        # the rejoin window, may shrink/grow, may raise GangBelowFloor —
        # which aborts the retry loop into run()'s fail-stop).
        self._plan_topology(exc)

    def run(self) -> int:
        """Supervise to completion: 0 when every member exited 0 (possibly
        after restarts and resizes), 1 when the budget is exhausted or the
        roster fell below ``min_workers`` (fail-stop, with a final
        structured line; checkpoints intact)."""
        from distributed_tensorflow_tpu.observability import tracing

        # One trace id per supervision (round 12): every Restart:/Resize:
        # journal event of this gang's life joins under it, so a shared
        # driver journal separates overlapping gangs.
        with tracing.trace(tracing.current_trace()):
            return self._run_supervised()

    def _run_supervised(self) -> int:
        self.metrics.gauge("world_size").set(len(self.active))
        if self.summary_writer is not None and self._elastic:
            # Initial world size, so the scalar stream starts at the
            # launched topology (resizes append to it at their restart
            # ordinal). Only in elastic mode: a fixed-size gang's tfevents
            # stay byte-identical to round 7.
            self.summary_writer.add_scalar(
                "world_size", float(len(self.active)), 0
            )
        try:
            if self.independent:
                # One incarnation for the whole run: member failures are
                # handled INSIDE _cycle (relaunch-alone) under the same
                # budget; a WorkerFailure escaping means the budget is
                # spent — the except below fail-stops it like an
                # exhausted retry loop.
                return self._cycle()
            return resilience.retry(
                self._cycle,
                attempts=self.max_restarts + 1,
                backoff=self.backoff,
                max_backoff=self.max_backoff,
                jitter=self.jitter,
                retry_on=(WorkerFailure,),
                describe="gang restart",
                on_retry=self._on_retry,
                sleep=self.sleep,
                rng=self.rng,
            )
        except GangBelowFloor as exc:
            lifecycle_event(
                "resize_denied",
                print_fn=self.print_fn,
                journal=self.journal,
                world=exc.world,
                min_workers=self.min_workers,
                restarts=self.restarts,
                max_restarts=self.max_restarts,
                cause=str(exc),
            )
            if self.summary_writer is not None:
                self.summary_writer.flush()
            return 1
        except WorkerFailure as exc:
            lifecycle_event(
                "restart_exhausted",
                print_fn=self.print_fn,
                journal=self.journal,
                restarts=self.restarts,
                max_restarts=self.max_restarts,
                cause=str(exc),
            )
            if self.summary_writer is not None:
                self.summary_writer.flush()
            return 1
        finally:
            self.metrics.flush_to(self.journal, component="elastic")
            self.journal.flush()
            if self.summary_writer is not None and self.restarts:
                self.summary_writer.flush()
