"""Elastic gang-restart: supervised multi-host recovery (round 7).

The reference's only failure behavior was gRPC blocking forever (SURVEY.md
§5 "Failure detection"); round 6 upgraded that to *fail-stop* — durable
CRC-verified checkpoints, preemption exit, anomaly rollback, and a chief
that detects a dead worker and ends the job cleanly (docs/multihost.md).
This module closes the loop from fail-stop to **fail-recover**: production
TPU training treats worker death as routine (PaLM-style runs restart the
gang from the newest checkpoint automatically; TorchElastic-style agents
supervise each rank under a restart budget), and round 6's durable
checkpoints are exactly the substrate that makes automatic restart correct.

Topology
--------
One :class:`ElasticAgent` per gang member, held by a driver (an
:class:`ElasticGang`): the agent spawns its worker process and watches two
signals —

- the **exit code** (a non-zero or premature exit is a death), and
- the **heartbeat verdict** from an agent-hosted detector
  (:class:`HeartbeatHealth` over ``runtime/native.py``'s UDP coordinator):
  beats stopped past ``timeout_ms`` is *dead*; beats flowing but the
  payload's monotonic progress counter frozen past ``stall_timeout_ms`` is
  *live-but-stalled* — the failure mode an exit code can never show (a rank
  hung in a collective keeps its native sender thread beating forever, and
  before round 7 the job simply hung with it).

On any failure the gang is restarted as a unit: every member is killed
(checkpoint state is durable; the dead epoch is repaid, not lost), the
restart budget (``TrainConfig.max_restarts``) is charged, the gang waits an
exponentially backed-off, jittered delay (``resilience.retry`` — the same
state machine checkpoint I/O uses), and every member is relaunched. The
relaunched processes re-bootstrap ``jax.distributed`` under
``cluster.bounded_initialize`` (bounded timeout + retry, so members that
come up before their coordinator get retried attempts instead of an
indefinite hang) and resume from the newest VALID checkpoint via
``Supervisor.prepare_or_restore``. Each restart emits a structured
``Restart:`` line and a ``restart`` tfevents scalar; an exhausted budget
falls back to round 6's fail-stop (non-zero driver exit, checkpoints
intact).

The detector is hosted by the AGENT, out-of-band of the job
(``cluster.bootstrap(heartbeat_host=...)``: every task, chief included,
becomes a plain sender) — in-band detection cannot recover a stall, because
the chief is stuck in the same collective as the stalled rank.

``tools/launch_local.py --max-restarts N`` is this module's multi-process
driver (the reference's nohup-per-task workflow, now supervised);
``tests/test_elastic.py`` pins the state machine on a fake process table
and ``tests/integration/test_fault_injection.py`` proves the SIGKILL →
gang-restart → resume → rc 0 path end to end.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

from distributed_tensorflow_tpu.train import resilience


class WorkerFailure(RuntimeError):
    """One or more gang members died or stalled. ``verdicts`` maps member
    name → verdict string (``rc=N``, ``dead``, ``stalled``, or
    ``straggler`` — still running past ``drain_timeout`` after a peer
    finished)."""

    def __init__(self, verdicts: dict):
        self.verdicts = dict(verdicts)
        super().__init__(
            " ".join(f"{n}={v}" for n, v in sorted(self.verdicts.items()))
        )


class HeartbeatHealth:
    """Progress-aware health verdicts over the agent-hosted UDP detector.

    Owns a fresh ``HeartbeatCoordinator`` (one per gang incarnation — the
    gang recreates this each cycle so a relaunch never inherits the killed
    incarnation's stale last-seen clocks). ``classify(worker_id)`` returns:

    - ``"dead"`` — reported once then silent past ``timeout_ms``, or never
      reported and the grace window (default 5× timeout) has elapsed;
    - ``"stalled"`` — beating, but the payload's progress counter frozen
      past ``stall_timeout_ms`` (0 disables stall detection). Workers that
      never reported progress are not judged — startup import/compile must
      not read as a stall;
    - ``"ok"`` — otherwise.
    """

    def __init__(
        self,
        port: int,
        expected_workers: int,
        *,
        timeout_ms: int = 5000,
        stall_timeout_ms: int = 0,
        grace_ms: int | None = None,
        clock=time.monotonic,
    ):
        from distributed_tensorflow_tpu.runtime import native

        self._coord = native.HeartbeatCoordinator(
            port, expected_workers, timeout_ms=timeout_ms, grace_ms=grace_ms
        )
        self._timeout_ms = int(timeout_ms)
        self._stall_ms = int(stall_timeout_ms)
        self._grace_ms = int(grace_ms if grace_ms is not None else 5 * timeout_ms)
        self._clock = clock
        self._start = clock()

    def classify(self, worker_id: int) -> str:
        since = self._coord.ms_since_seen(worker_id)
        if since < 0:  # never reported
            elapsed_ms = (self._clock() - self._start) * 1000.0
            return "dead" if elapsed_ms > self._grace_ms else "ok"
        if since > self._timeout_ms:
            return "dead"
        if self._stall_ms > 0:
            since_progress = self._coord.ms_since_progress(worker_id)
            if since_progress > self._stall_ms:
                return "stalled"
        return "ok"

    def stop(self) -> None:
        self._coord.stop()


class ElasticAgent:
    """Supervises ONE gang member: spawn, poll the exit code, kill.

    ``spawn_fn()`` returns a process handle exposing ``poll() -> rc|None``
    and ``kill()`` (``subprocess.Popen`` satisfies it; the fast-tier tests
    drive the whole machine with a fake process table). ``worker_id`` is
    the member's slot in the heartbeat detector."""

    def __init__(self, name: str, spawn_fn: Callable, *, worker_id: int | None = None):
        self.name = name
        self.worker_id = worker_id
        self._spawn_fn = spawn_fn
        self.handle = None

    def start(self):
        self.handle = self._spawn_fn()
        return self.handle

    def poll(self):
        """Exit code, or None (running / not yet started)."""
        return None if self.handle is None else self.handle.poll()

    def kill(self) -> None:
        """Hard-kill a live member (SIGKILL semantics — a rank hung in a
        collective ignores SIGTERM forever; its state is durable in the
        checkpoint, so the restart repays at most one epoch)."""
        if self.handle is None or self.handle.poll() is not None:
            return
        self.handle.kill()
        wait = getattr(self.handle, "wait", None)
        if wait is not None:  # reap, so the driver never accumulates zombies
            try:
                wait(timeout=30)
            except Exception:  # noqa: BLE001 — unkillable is the OS's problem
                pass


class ElasticGang:
    """The driver: N agents supervised as one gang under a restart budget.

    ``run()`` starts every member and polls until either every member has
    exited 0 (return 0) or a failure verdict lands — non-zero exit, dead,
    or stalled — at which point every live member is killed and the gang is
    relaunched after an exponentially backed-off, jittered delay, at most
    ``max_restarts`` times (``resilience.retry`` is the backoff state
    machine; ``max_restarts=0`` preserves round 6's fail-stop exactly:
    first failure → kill survivors → return 1). Each restart emits a
    structured ``Restart:`` line and, when a ``summary_writer`` is given, a
    ``restart`` tfevents scalar (value = restart ordinal).

    ``health_factory`` builds a fresh :class:`HeartbeatHealth` per gang
    incarnation (fresh detector state — a relaunch must not inherit the
    killed incarnation's silence). Once the first member exits 0, the rest
    must finish within ``drain_timeout`` seconds or the still-running
    members are verdicted ``straggler`` (a peer wedged in a collective the
    finished member will never rejoin beats forever — without the drain
    window the gang would hang with no verdict). ``sleep``/``clock``/
    ``poll_interval`` are injectable so the fast-tier tests run the whole
    machine without wall time or real processes."""

    def __init__(
        self,
        agents: Sequence[ElasticAgent],
        *,
        max_restarts: int = 0,
        backoff: float = 1.0,
        max_backoff: float = 30.0,
        jitter: float = 0.25,
        health_factory: Callable[[], HeartbeatHealth] | None = None,
        poll_interval: float = 0.5,
        drain_timeout: float = 300.0,
        print_fn=print,
        summary_writer=None,
        sleep=time.sleep,
        clock=time.monotonic,
        rng=None,
    ):
        self.agents = list(agents)
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.health_factory = health_factory
        self.poll_interval = float(poll_interval)
        self.drain_timeout = float(drain_timeout)
        self.print_fn = print_fn
        self.summary_writer = summary_writer
        self.sleep = sleep
        self.clock = clock
        self.rng = rng
        self.restarts = 0  # restarts actually performed

    # -- one gang incarnation --------------------------------------------

    def _cycle(self) -> int:
        health = None
        first_done = None  # clock() when the first member exited 0
        try:
            for agent in self.agents:
                agent.start()
            health = self.health_factory() if self.health_factory else None
            while True:
                rcs = {a.name: a.poll() for a in self.agents}
                verdicts = {
                    name: f"rc={rc}"
                    for name, rc in rcs.items()
                    if rc is not None and rc != 0
                }
                if health is not None:
                    for a in self.agents:
                        if rcs[a.name] is None and a.worker_id is not None:
                            v = health.classify(a.worker_id)
                            if v != "ok":
                                verdicts[a.name] = v
                # Premature-exit guard: once any member finishes (rc 0),
                # the rest must drain within drain_timeout — a peer blocked
                # in a collective the finished member will never rejoin
                # would otherwise beat forever ("ok" to health) and hang
                # the gang with no verdict at all. Staggered-but-honest
                # completion finishes well inside the window.
                if not verdicts and any(rc == 0 for rc in rcs.values()):
                    if first_done is None:
                        first_done = self.clock()
                    elif self.clock() - first_done > self.drain_timeout:
                        verdicts = {
                            name: "straggler"
                            for name, rc in rcs.items()
                            if rc is None
                        }
                if verdicts:
                    # Gang semantics: one bad member poisons the incarnation
                    # (its peers are blocked in collectives it will never
                    # join) — kill every survivor and hand the verdicts up.
                    for a in self.agents:
                        a.kill()
                    raise WorkerFailure(verdicts)
                if all(rc == 0 for rc in rcs.values()):
                    return 0
                self.sleep(self.poll_interval)
        except WorkerFailure:
            raise
        except BaseException:
            # Not a gang verdict: spawn/detector failure (e.g. the
            # heartbeat port got grabbed between incarnations) or a driver
            # bug. The already-started members must not outlive the driver
            # as orphans holding the checkpoint dir.
            for agent in self.agents:
                agent.kill()
            raise
        finally:
            if health is not None:
                health.stop()

    def _on_retry(self, exc: WorkerFailure, attempt: int, delay: float) -> None:
        self.restarts = attempt + 1
        # Structured, greppable — same key=value shape as Preemption:/Rollback:.
        self.print_fn(
            f"Restart: restart={self.restarts}/{self.max_restarts} "
            f"cause[{exc}] backoff_s={delay:.1f}"
        )
        if self.summary_writer is not None:
            self.summary_writer.add_scalar(
                "restart", float(self.restarts), self.restarts
            )

    def run(self) -> int:
        """Supervise to completion: 0 when every member exited 0 (possibly
        after restarts), 1 when the budget is exhausted (fail-stop, with a
        final ``Restart: budget exhausted`` line; checkpoints intact)."""
        try:
            return resilience.retry(
                self._cycle,
                attempts=self.max_restarts + 1,
                backoff=self.backoff,
                max_backoff=self.max_backoff,
                jitter=self.jitter,
                retry_on=(WorkerFailure,),
                describe="gang restart",
                on_retry=self._on_retry,
                sleep=self.sleep,
                rng=self.rng,
            )
        except WorkerFailure as exc:
            self.print_fn(
                f"Restart: budget exhausted restarts={self.restarts}/"
                f"{self.max_restarts} cause[{exc}] — failing stop "
                "(checkpoints intact; newest valid step restores on the "
                "next launch)"
            )
            if self.summary_writer is not None:
                self.summary_writer.flush()
            return 1
        finally:
            if self.summary_writer is not None and self.restarts:
                self.summary_writer.flush()
