"""Whole-run compilation: every epoch, shuffle, and eval in ONE dispatch.

The reference's entire experiment is a fixed program — 100 epochs × 550
batches of SGD with a per-epoch test-set eval (reference tfsingle.py:72-95,
tfdist_between.py:86-111) — executed as ~55,000 Python→runtime round trips.
train/scan.py collapses one epoch into one dispatch; this module collapses
the *run*: a nested ``lax.scan`` (epochs over steps) with the epoch shuffle
performed on-device (``jax.random.permutation`` + gather) and the per-epoch
test accuracy computed in-graph, so the host dispatches once and receives
the full training history — per-step costs ``[epochs, steps]`` and
per-epoch accuracies ``[epochs]`` — in a single D2H transfer.

Why this is the TPU-shaped design (and not just a bigger batch of the same):

- The train set is staged in HBM **once** (~172 MB f32 for MNIST) instead
  of per-epoch; each epoch re-reads it through a fresh permutation gather.
- Zero host round trips between epochs — on a tunneled/remote chip each
  round trip costs ~20-40 ms, comparable to the whole on-device epoch.
- Eval rides the same program: the ``[10000, 784]`` test matmul is a large
  MXU-friendly shape, cheaper than shipping params to the host would be.

Semantics vs the eager loop: identical update rule, batch size, and update
count (``state.step`` advances ``epochs × steps``). The shuffle uses JAX's
on-device PRNG instead of the host numpy generator, so batch *composition*
differs from the host-shuffled paths run-to-run the same way two host seeds
differ from each other — distributionally equivalent, bit-different
(SURVEY.md §7 hard-part b treats init seeds the same way). With
``shuffle=False`` batches are taken in dataset order every epoch — the same
update sequence as ``train/scan.py`` over unshuffled staging, equal to
ulp-level (the gather-built batch may reassociate float ops vs the sliced
batch); tests/test_compiled_run.py asserts that parity.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from distributed_tensorflow_tpu.ops import losses as losses_lib
from distributed_tensorflow_tpu.parallel.strategy import TrainState, _loss_from_model


def wrapped_epoch_perm(sub, *, domain: int, need: int, k: int, shuffle: bool):
    """One epoch's index stream over a device-resident dataset of ``domain``
    rows: ``need`` indices drawn from ``k`` fresh full permutations
    concatenated (the on-device analog of ``DataSet.next_batch``'s
    tail-carry reshuffle; ``k == 1`` is the plain single-permutation epoch),
    or dataset order tiled when not shuffling. Shared by the generic and
    async compiled-run builders so the wrap convention cannot diverge."""
    if not shuffle:
        return jnp.tile(jnp.arange(domain), k)[:need]
    if k == 1:
        return jax.random.permutation(sub, domain)[:need]
    subs = jax.random.split(sub, k)
    return jnp.concatenate(
        [jax.random.permutation(s, domain) for s in subs]
    )[:need]


def make_compiled_run_fn(
    model,
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    batch_size: int,
    epochs: int,
    shuffle: bool = True,
    batch_sharding=None,
    donate: bool = True,
    steps_per_epoch: int | None = None,
) -> Callable:
    """Build ``fn(state, train_x, train_y, test_x, test_y, key) ->
    (state, {"costs": [epochs, steps], "accuracy": [epochs]})`` — the whole
    training run as one jitted program.

    ``train_x``/``train_y`` are the full (un-batched) arrays; the step count
    is ``len(train_x) // batch_size`` (tail dropped, matching the reference's
    ``int(num_examples/batch_size)``, reference tfdist_between.py:87).
    ``key`` is a ``jax.random`` key driving the per-epoch shuffles. With
    ``batch_sharding`` (a NamedSharding over the ``data`` axis) each gathered
    batch is sharded across chips → sync data-parallel, GSPMD inserting the
    gradient all-reduce.

    ``steps_per_epoch`` overrides the step count (the reference's
    ``per_worker_epoch`` convention: N workers × num_examples/100 steps,
    reference tfdist_between.py:87); the per-epoch index stream then wraps
    across as many fresh full-dataset permutations as needed — the on-device
    analog of ``DataSet.next_batch``'s tail-carry reshuffle.
    """

    @partial(jax.jit, donate_argnums=0 if donate else ())
    def run(state: TrainState, train_x, train_y, test_x, test_y, key):
        steps = (
            train_x.shape[0] // batch_size
            if steps_per_epoch is None
            else steps_per_epoch
        )
        need = steps * batch_size
        # Permutation domain: the trimmed dataset for the plain convention
        # (old behavior bit-preserved), the full dataset when wrapping.
        domain = need if steps_per_epoch is None else train_x.shape[0]
        k = (need + domain - 1) // domain if need else 1

        def epoch_perm(sub):
            return wrapped_epoch_perm(
                sub, domain=domain, need=need, k=k, shuffle=shuffle
            )

        def train_step(state: TrainState, idx):
            x = jnp.take(train_x, idx, axis=0)
            y = jnp.take(train_y, idx, axis=0)
            if batch_sharding is not None:
                x = jax.lax.with_sharding_constraint(x, batch_sharding)
                y = jax.lax.with_sharding_constraint(y, batch_sharding)
            cost, grads = jax.value_and_grad(
                partial(_loss_from_model, model, loss_fn)
            )(state.params, x, y)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1), cost

        def epoch_body(carry, _):
            state, key = carry
            key, sub = jax.random.split(key)
            perm = epoch_perm(sub)
            state, costs = jax.lax.scan(
                train_step, state, perm.reshape(steps, batch_size)
            )
            acc = losses_lib.accuracy(model.apply(state.params, test_x), test_y)
            return (state, key), (costs, acc)

        (state, _), (costs, accs) = jax.lax.scan(
            epoch_body, (state, key), None, length=epochs
        )
        return state, {"costs": costs, "accuracy": accs}

    return run
