"""Deterministic fault-injection registry (round 19, docs/resilience.md
§failpoints).

The reference's value proposition is surviving flaky workers (the async>
sync thesis), and rounds 6/8/16/17 rebuilt that on TPU — but every fault
proof so far was a bespoke integration script (one SIGKILL, one
throttle). This module makes faults a first-class, *repeatable* input:
named failpoints threaded through every durability seam the repo has
(checkpoint save/restore + manifests, the DiLoCo ``DeltaExchange``
mailbox, the serving fleet's ``MailboxClient``, journal appends/rotation,
elastic relaunch/health probes) fire deterministic faults armed via the
``DTF_FAILPOINTS`` env var or the :func:`configure`/:func:`arm` API.
``tools/chaos_sweep.py`` sweeps schedules of these faults over seeds and
asserts the invariants the docs claim (no data loss, oracles met, rc 0).

Spec grammar (comma-separated entries)::

    DTF_FAILPOINTS="name:kind[=arg][@N[+]],..."

    kind  = raise | torn | delay | kill
    =arg  = delay seconds (delay only; default 0.01)
    @N    = fire on the Nth hit of the name (1-based; default 1)
    +     = keep firing on every hit >= N (default: the Nth hit only)

Examples::

    DTF_FAILPOINTS="ckpt.manifest:torn@2"          # tear save 2's manifest
    DTF_FAILPOINTS="delta.load:raise"              # first peer read fails
    DTF_FAILPOINTS="atomic.write.commit:kill@3"    # SIGKILL mid-commit 3
    DTF_FAILPOINTS="journal.append:delay=0.05@1+"  # every append slow

Fault kinds at a hit:

- ``raise`` — raise :class:`FailpointError` (an ``OSError`` subclass, so
  the retry/verify machinery under test treats it exactly like a real
  I/O hiccup — ``resilience.retry_io`` absorbs a transient one).
- ``delay`` — ``time.sleep(arg)`` (races, staleness, backoff windows).
- ``kill`` — SIGKILL this process (the crash cases: a writer dying
  mid-commit must leave only a ``.tmp`` orphan + a missing manifest).
- ``torn`` — corrupt the COMMITTED file at a tear-capable seam
  (truncate to half): atomic replace already protects readers from torn
  *tmp* files, so ``torn`` models the storage layer corrupting committed
  bytes — exactly what the CRC-on-read hardening must catch.

Sites call :func:`fire` (one hit counted per operation; evaluates
raise/delay/kill specs) and — at tear-capable seams, AFTER the commit —
:func:`tear`, which consults the same hit counter ``fire`` just advanced
and never counts its own. Every registered name is listed in
:data:`REGISTERED` and documented in docs/resilience.md §failpoints
(cross-checked by tests/test_failpoints.py — the round-12 "widen
knowingly" discipline applied to fault names).

Default-off contract: with nothing armed, :func:`fire`/:func:`tear`
return after one falsy check — every hardened path is behaviorally
identical to round 18 (pinned by the existing suites). Determinism:
per-name hit counters under a lock, no wall clock, no RNG — the same
schedule against the same code path faults the same operation every run.

jax-free by design (the lean-import convention): the elastic driver,
``serve_fleet``, and the observability package all hook this module on
degraded containers.
"""

from __future__ import annotations

import os
import signal
import threading
import time

ENV_VAR = "DTF_FAILPOINTS"

_KINDS = ("raise", "torn", "delay", "kill")

# The seam inventory: every name a ``fire``/``tear`` call site uses, with
# where it sits. docs/resilience.md §failpoints documents each (test-
# pinned); arming an unknown name raises at configure time — a typo'd
# schedule must be loud, not silently inert.
REGISTERED = {
    "atomic.write": (
        "resilience.write_json_atomic entry (+ tear of the committed "
        "file): checkpoint manifests, layout sidecars, fleet mailbox JSON"
    ),
    "atomic.write.commit": (
        "resilience.write_json_atomic between the tmp write and the "
        "atomic replace — kill here leaves a .tmp orphan, no commit"
    ),
    "ckpt.save": "supervisor.Supervisor.save entry (before the orbax write)",
    "ckpt.restore": (
        "supervisor.Supervisor.prepare_or_restore, per candidate step "
        "before its restore attempt"
    ),
    "ckpt.manifest": (
        "resilience.write_manifest (+ tear of the committed manifest "
        "sidecar)"
    ),
    "ckpt.async": (
        "resilience.AsyncCheckpointWriter worker, before executing a "
        "queued write — raise: the writer dies before serializing (the "
        "queued step never lands, error deferred to wait_pending); "
        "kill: crash mid-async-write; delay: makes supersession "
        "deterministic"
    ),
    "delta.post": (
        "local_sgd.DeltaExchange.post entry (+ tear of the committed "
        "npz post)"
    ),
    "delta.post.commit": (
        "DeltaExchange.post between the tmp write and the atomic "
        "replace — kill here leaves a .tmp orphan in the mailbox"
    ),
    "delta.load": (
        "DeltaExchange._load entry — raise is a transient unreadable "
        "peer post (retried next boundary, watermark unmoved)"
    ),
    "fleet.submit": (
        "serve_fleet.MailboxClient.submit entry (+ tear of the "
        "committed request file)"
    ),
    "fleet.result": (
        "serve_fleet.MailboxClient.put_result entry (+ tear of the "
        "committed result file)"
    ),
    "fleet.read": (
        "serve_fleet._read_dir entry (take_inbox and poll_results both "
        "pass through it)"
    ),
    "fleet.migrate": (
        "serve_fleet.MigrationStore.post entry (+ tear of the committed "
        "KV-migration npz envelope) — a torn post is quarantined once at "
        "load and the request falls back to re-prefill on the decode "
        "replica"
    ),
    "journal.append": "observability EventJournal.emit, before the os.write",
    "journal.rotate": "observability EventJournal._rotate entry",
    "elastic.relaunch": "elastic.ElasticAgent.start entry (every spawn)",
    "elastic.health": "elastic.HttpHealth.probe entry (every probe)",
}


class FailpointError(OSError):
    """The injected fault. Subclasses ``OSError`` deliberately: the
    seams under test retry/skip on OSError, so an injected transient
    exercises the SAME recovery path a real filesystem hiccup would."""


class _Spec:
    __slots__ = ("name", "kind", "hit", "persistent", "arg")

    def __init__(self, name, kind, hit, persistent, arg):
        self.name = name
        self.kind = kind
        self.hit = hit
        self.persistent = persistent
        self.arg = arg

    def matches(self, count: int) -> bool:
        return count >= self.hit if self.persistent else count == self.hit

    def describe(self) -> str:
        out = f"{self.name}:{self.kind}"
        if self.kind == "delay":
            out += f"={self.arg}"
        out += f"@{self.hit}" + ("+" if self.persistent else "")
        return out


_lock = threading.Lock()
_specs: dict[str, list[_Spec]] = {}
_hits: dict[str, int] = {}
_in_fire = threading.local()


def _parse_entry(entry: str) -> _Spec:
    entry = entry.strip()
    if ":" not in entry:
        raise ValueError(
            f"failpoint entry {entry!r}: expected 'name:kind[=arg][@N[+]]'"
        )
    name, _, rest = entry.partition(":")
    name = name.strip()
    if name not in REGISTERED:
        raise ValueError(
            f"unknown failpoint name {name!r} — registered names: "
            f"{', '.join(sorted(REGISTERED))}"
        )
    hit, persistent = 1, False
    if "@" in rest:
        rest, _, hit_s = rest.partition("@")
        hit_s = hit_s.strip()
        if hit_s.endswith("+"):
            persistent = True
            hit_s = hit_s[:-1]
        hit = int(hit_s)
        if hit < 1:
            raise ValueError(f"failpoint {entry!r}: @N must be >= 1")
    kind, _, arg_s = rest.partition("=")
    kind = kind.strip()
    if kind not in _KINDS:
        raise ValueError(
            f"failpoint {entry!r}: kind must be one of {_KINDS}, got "
            f"{kind!r}"
        )
    arg = 0.01
    if arg_s:
        if kind != "delay":
            raise ValueError(
                f"failpoint {entry!r}: only 'delay' takes '=arg'"
            )
        arg = float(arg_s)
    return _Spec(name, kind, hit, persistent, arg)


def configure(spec: str | None) -> None:
    """Replace the armed registry from a spec string (the env grammar);
    ``None``/empty disarms everything. Hit counters reset — a schedule
    is deterministic from the moment it arms."""
    global _specs, _hits
    with _lock:
        new: dict[str, list[_Spec]] = {}
        for entry in (spec or "").split(","):
            if not entry.strip():
                continue
            s = _parse_entry(entry)
            new.setdefault(s.name, []).append(s)
        _specs = new
        _hits = {}


def arm(entry: str) -> None:
    """Arm one additional entry (``name:kind[=arg][@N[+]]``) on top of
    whatever is already armed; its name's hit counter resets."""
    s = _parse_entry(entry)
    with _lock:
        _specs.setdefault(s.name, []).append(s)
        _hits.pop(s.name, None)


def reset() -> None:
    """Re-arm from the environment (``DTF_FAILPOINTS``), clearing any
    programmatic arms and all hit counters."""
    configure(os.environ.get(ENV_VAR))


def active() -> dict[str, list[str]]:
    """``{name: [spec, ...]}`` of everything armed (for reports/tests)."""
    with _lock:
        return {
            name: [s.describe() for s in specs]
            for name, specs in _specs.items()
        }


def hit_count(name: str) -> int:
    """How many times ``fire(name)`` has been hit since arming."""
    if name not in REGISTERED:
        raise ValueError(f"unknown failpoint name {name!r}")
    with _lock:
        return _hits.get(name, 0)


def _emit_event(name: str, kind: str, hit: int) -> None:
    # Through the process-default journal (jax-free; a NullJournal when
    # unconfigured). The _in_fire guard above us already blocks the
    # journal seam's own failpoint from recursing through here.
    try:
        from distributed_tensorflow_tpu.observability import (
            journal as obs_journal,
        )

        obs_journal.emit(
            "failpoint", name=name, fault=kind, hit=int(hit)
        )
    except Exception:  # pragma: no cover — never let telemetry mask a fault
        pass


def fire(name: str) -> None:
    """Hit the named failpoint: count the hit and act on any armed
    raise/delay/kill spec whose ``@N`` matches (``torn`` specs are inert
    here — they act in :func:`tear`, after the site's commit). No-op
    (one falsy check) when nothing is armed."""
    if not _specs:
        return
    if getattr(_in_fire, "active", False):
        return  # reentrant (a failpoint event's own journal append)
    with _lock:
        specs = _specs.get(name)
        if specs is None:
            return
        _hits[name] = count = _hits.get(name, 0) + 1
        matched = [s for s in specs if s.kind != "torn" and s.matches(count)]
    if not matched:
        return
    _in_fire.active = True
    try:
        for s in matched:
            _emit_event(name, s.kind, count)
            if s.kind == "delay":
                time.sleep(s.arg)
            elif s.kind == "raise":
                raise FailpointError(
                    f"injected failpoint {s.describe()} (hit {count})"
                )
            elif s.kind == "kill":
                _flush_journal()
                os.kill(os.getpid(), signal.SIGKILL)
    finally:
        _in_fire.active = False


def tear(name: str, path: str) -> bool:
    """Tear-capable seams call this AFTER their atomic commit, with the
    committed path: when a ``torn`` spec for ``name`` matches the hit
    counter the site's :func:`fire` just advanced, the committed file is
    truncated to half its bytes (the storage-corruption model the CRC
    envelopes must catch). Returns True when it tore. Never counts a
    hit of its own — a site's fire() and tear() describe ONE operation."""
    if not _specs:
        return False
    with _lock:
        specs = _specs.get(name)
        if specs is None:
            return False
        count = _hits.get(name, 0)
        matched = [
            s for s in specs if s.kind == "torn" and s.matches(count)
        ]
    if not matched:
        return False
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))
    _in_fire.active = True
    try:
        _emit_event(name, "torn", count)
    finally:
        _in_fire.active = False
    return True


def _flush_journal() -> None:
    try:
        from distributed_tensorflow_tpu.observability import (
            journal as obs_journal,
        )

        obs_journal.get_journal().flush()
    except Exception:  # pragma: no cover
        pass


# Arm from the environment at import: subprocess workers (the chaos
# sweep's kill/crash scenarios) receive their schedule via DTF_FAILPOINTS
# with zero worker code. In-process tests use configure()/arm()/reset().
reset()
