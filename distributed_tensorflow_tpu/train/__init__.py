"""Training layer: loop, supervisor, resilience, elastic agents.

Lazy exports (PEP 562, same pattern as the package root): the elastic
agent/driver half of this package (`elastic.py`, consumed by
`tools/launch_local.py`) supervises OS processes and must stay importable
in a lean supervisor process — or a degraded container — that has no
working jax; only touching `Trainer`/`LMTrainer`/`Supervisor` pulls the
jax-backed training stack in.
"""

_LAZY_EXPORTS = {
    "Trainer": ("distributed_tensorflow_tpu.train.trainer", "Trainer"),
    "LMTrainer": ("distributed_tensorflow_tpu.train.lm_trainer", "LMTrainer"),
    "Supervisor": ("distributed_tensorflow_tpu.train.supervisor", "Supervisor"),
    "ElasticAgent": ("distributed_tensorflow_tpu.train.elastic", "ElasticAgent"),
    "ElasticGang": ("distributed_tensorflow_tpu.train.elastic", "ElasticGang"),
    "HeartbeatHealth": (
        "distributed_tensorflow_tpu.train.elastic",
        "HeartbeatHealth",
    ),
    "DiLoCoState": (
        "distributed_tensorflow_tpu.train.local_sgd",
        "DiLoCoState",
    ),
}

__all__ = list(_LAZY_EXPORTS)


def __getattr__(name):
    try:
        module, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value  # cache: next access skips __getattr__
    return value
