from distributed_tensorflow_tpu.train.trainer import Trainer  # noqa: F401
from distributed_tensorflow_tpu.train.lm_trainer import LMTrainer  # noqa: F401
from distributed_tensorflow_tpu.train.supervisor import Supervisor  # noqa: F401
from distributed_tensorflow_tpu.train.elastic import (  # noqa: F401
    ElasticAgent,
    ElasticGang,
    HeartbeatHealth,
)
